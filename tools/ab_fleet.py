"""Same-session A/B of the feasibility-indexed scheduler at fleet scale
(PERF.md round 19).

Runs ``tools/ray_perf.py --fleet-only`` alternately with the index ON
(HEAD defaults) and OFF (``--no-sched-index``: every placement decision
takes the original full-scan ``pick_node`` path, byte-identical to the
pre-round-19 scheduler) on the SAME commit, interleaved so ambient box
load hits both arms equally (the round-3 lesson). Both arms replay the
SAME seeded lease schedule against the in-process fleet emulator at
100/500/1,000 emulated nodes. Watch:

    fleet_place_p99_ms_1000   THE acceptance row — the index arm must be
                              >=2x better than the scan arm at 1,000 nodes
    fleet_place_p50_ms_*      scan grows linearly with fleet size; the
                              index stays flat (bounded probe quota)
    fleet_decision_digest_*   per-arm determinism witness: each arm's
                              digest must be identical across rounds (the
                              kill-switch arm's digest IS the pre-change
                              decision sequence). The arms legitimately
                              DIFFER from each other: hybrid picks max
                              headroom over a bounded sample, not over
                              every view.

    python tools/ab_fleet.py [--rounds 3] [--full]

bench.py records the same pair per round as the ``fleet_scale`` BENCH
record.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ab_coalesce import run_once  # noqa: E402 — shared machinery

SCALES = (100, 500, 1000)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument(
        "--full", action="store_true", help="full (not --quick) perf runs"
    )
    args = ap.parse_args()

    on_runs: list = []
    off_runs: list = []
    for i in range(args.rounds):
        order = [
            (("--fleet-only",), on_runs, "on "),
            (("--fleet-only", "--no-sched-index"), off_runs, "off"),
        ]
        if i % 2:
            order.reverse()
        for flags, sink, arm in order:
            print(f"[round {i}] fleet {arm} ...", flush=True)
            sink.append(run_once(quick=not args.full, extra_flags=flags))

    summary: dict = {}
    print(f"\n{'metric':<32} {'index':>12} {'scan':>12} {'scan/index':>11}")
    for n in SCALES:
        for q in ("p50", "p99"):
            k = f"fleet_place_{q}_ms_{n}"
            on_med = statistics.median(r[k] for r in on_runs)
            off_med = statistics.median(r[k] for r in off_runs)
            # scan/index: >1 means the index is faster; the acceptance
            # bar is >=2.0 on fleet_place_p99_ms_1000.
            ratio = off_med / on_med if on_med else float("inf")
            summary[k] = {
                "index": on_med, "scan": off_med, "ratio": round(ratio, 2),
            }
            print(f"{k:<32} {on_med:>12.4f} {off_med:>12.4f} {ratio:>11.2f}")
    for k in ("fleet_hb_ingest_us", "fleet_delta_bytes_per_node"):
        on_med = statistics.median(r[k] for r in on_runs)
        off_med = statistics.median(r[k] for r in off_runs)
        summary[k] = {"index": on_med, "scan": off_med}
        print(f"{k:<32} {on_med:>12.1f} {off_med:>12.1f}")

    # Determinism witness: each arm must replay decision-for-decision
    # across rounds; the scan arm's digest is the pre-change sequence.
    for n in SCALES:
        k = f"fleet_decision_digest_{n}"
        for arm, runs in (("index", on_runs), ("scan", off_runs)):
            digests = {r[k] for r in runs}
            stable = len(digests) == 1
            summary[f"{k}_{arm}_stable"] = stable
            print(
                f"{k} [{arm}]: {sorted(digests)} "
                f"({'stable' if stable else 'NON-DETERMINISTIC'})"
            )
            if not stable:
                print("FAIL: decision replay diverged across rounds")
                print(json.dumps(summary), flush=True)
                return 1
    bar = summary["fleet_place_p99_ms_1000"]["ratio"]
    print(
        f"\nacceptance: p99@1000 scan/index = {bar:.2f}x "
        f"({'PASS' if bar >= 2.0 else 'FAIL'} against the >=2x bar)"
    )
    print(json.dumps(summary), flush=True)
    return 0 if bar >= 2.0 else 1


if __name__ == "__main__":
    sys.exit(main())
