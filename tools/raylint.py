"""raylint — AST-level concurrency, invariant & TPU/JAX lint for ray_tpu.

The runtime carries load-bearing invariants that exist only by convention:
a hybrid asyncio + ``threading.Lock`` concurrency model, RPC allowlists in
``core/protocol.py``, env-var kill switches, a long tail of broad
``except Exception`` blocks — and, since the host-free train loop and the
cache-aware serving tier, a *device plane* whose throughput depends on no
host synchronization inside hot paths. This tool machine-checks those
properties the way ``tools/metrics_lint.py`` checks the series catalog —
CI-enforced via ``tests/test_raylint.py``.

Rule families
-------------
Concurrency / invariants (RL0xx):

RL001  blocking call inside ``async def`` (``time.sleep``, blocking
       socket/subprocess/file I/O, zero-arg ``Future.result()``,
       ``Lock.acquire()`` without a timeout).
RL002  ``threading.Lock``/``RLock`` held across an ``await``.
RL003  fire-and-forget task: ``asyncio.ensure_future``/``create_task``
       whose result is discarded. Use ``ray_tpu.util.tasks.spawn``.
RL004  env-var hygiene: every ``RAY_TPU_*`` read outside
       ``core/config.py`` must be a registered bootstrap var; knob
       reads go through ``GLOBAL_CONFIG``; README stays complete.
RL005  RPC-contract consistency: allowlist entries resolve to
       registered ``_h_<meth>`` / ``_h_<topic>_<meth>`` handlers.
RL006  silent exception swallowing (broad except, body acts on nothing).

TPU/JAX device plane (RL1xx, "jaxlint"):

RL101  host–device sync in device-hot code: ``jax.device_get``,
       ``np.asarray``, ``.item()``, ``.block_until_ready()`` inside a
       function reachable from a jit/shard_map dispatch site or a
       device-hot entrypoint (``LLMEngine.step``, ``TrainContext.report``,
       ``Learner.update``) via the static call graph; plus
       ``float()/int()/bool()`` concretization inside *traced* functions.
RL102  recompilation hazards: ``jax.jit``/``shard_map`` constructed
       inside a loop, jit-wrapped-and-immediately-called (retraces every
       invocation), and data-dependent ``static_argnums``/``argnames``.
RL103  donation hygiene: a donated argument read after the jitted call
       (its buffer is invalidated); step-shaped jits with no donation
       are reported as ADVISORY findings (flagged, never fail the exit
       code — but the tree convention is to pragma-justify them).
RL104  collective-order divergence: a collective op under a rank-/slice-
       conditional branch in ``util/collective/``, ``rllib/learner.py``
       or ``train/`` — divergent collective ordering across ranks hangs
       the group.
RL105  lock-order deadlock: the cross-file lock-acquisition graph over
       every ``threading.Lock``/``RLock`` holder (edges = lock B acquired
       — directly or through the call graph — while lock A is held);
       any AB/BA cycle is a finding carrying both witness paths. A
       non-reentrant ``Lock`` re-acquired while held is a self-deadlock
       finding.

RL000  malformed suppression pragma (unknown rule id or missing reason).

Device-hot reachability (RL101)
-------------------------------
A function is *device-hot* when it (a) calls a callable bound from
``jax.jit(...)``/``shard_map(...)`` (a dispatch site), (b) is one of the
registered entrypoints in ``DEVICE_HOT_ENTRYPOINTS``, or (c) is reachable
from either through the static call graph (bare names, ``self.meth``,
``module.func``, ``self.attr.meth`` via instance typing, nested defs).
A function is *traced* when it is passed into ``jax.jit``/``shard_map``/
``jax.grad``/``jax.value_and_grad`` (or decorated with one), or reachable
from such a function.

Suppression
-----------
``# raylint: disable=RL006 -- <reason>`` on the finding's line (or on a
comment-only line directly above it). The reason string is REQUIRED —
a pragma without one is itself a finding (RL000) and fails CI.

Caching & incrementality
------------------------
Per-file analysis facts (findings + call-graph/lock facts) are cached
under ``.raylint_cache/`` keyed by a content hash (file source + the
raylint source itself), so unchanged files never re-parse. Cross-file
analyses (RL004/RL005/RL101/RL105) always re-run over the cached facts —
they are cheap without the parse. ``--changed-only`` reports only
findings in files changed vs git HEAD (cross-file analysis still sees
the whole tree, so reachability and the lock graph stay sound).

Run::

    python tools/raylint.py              # lint ray_tpu/, exit 1 on findings
    python tools/raylint.py --json       # machine-readable findings + counts
    python tools/raylint.py --only RL003,RL006
    python tools/raylint.py --only jax       # the RL101-RL104 family
    python tools/raylint.py --only locks     # RL105 lock-order analysis
    python tools/raylint.py --only metrics   # the metrics-catalog lint
    python tools/raylint.py --changed-only   # findings in git-changed files
    python tools/raylint.py --no-cache       # bypass .raylint_cache/

Adding a rule: subclass ``Rule``, set ``ID``/``TITLE``, implement
``check(ctx)`` (per-file) and/or ``finalize(tree_ctx)`` (whole-tree, over
the facts layer), and append it to ``ALL_RULES``. Add the three fixtures
(violating / clean / pragma-suppressed) in tests/test_raylint.py and a
row to the README table.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import hashlib
import json
import os
import re
import subprocess
import sys
from typing import Iterable, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Bumping this (or editing this file at all — the source is part of the
# cache key) invalidates every .raylint_cache entry.
SCHEMA_VERSION = "3"
CACHE_DIRNAME = ".raylint_cache"

PRAGMA_RE = re.compile(
    r"#\s*raylint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s+--\s*(?P<reason>.*\S))?\s*$"
)
ENV_PREFIX = "RAY_TPU_"

# Socket-module calls that actually block on the network. gethostname()
# and friends are local libc lookups and deliberately NOT listed.
_BLOCKING_SOCKET = {
    "create_connection",
    "getaddrinfo",
    "gethostbyname",
    "gethostbyname_ex",
    "gethostbyaddr",
    "getfqdn",
}
_BLOCKING_SUBPROCESS = {
    "run",
    "call",
    "check_call",
    "check_output",
    "getoutput",
    "getstatusoutput",
    "Popen",
}

# RL101: host-side functions that anchor device-hot reachability even
# though they do not themselves dispatch a jitted callable (they sit
# BETWEEN dispatches on the steady-state step path). Dotted module +
# qualname, matched against the scanned tree.
DEVICE_HOT_ENTRYPOINTS = frozenset(
    {
        "ray_tpu.llm.engine.LLMEngine.step",
        "ray_tpu.llm.engine.LLMEngine.generate",
        # The speculative-decoding draft/verify cycle runs inside every
        # spec-eligible engine step (round 16).
        "ray_tpu.llm.spec_decode.SpecDecoder.step",
        "ray_tpu.llm.spec_decode.SpecDecoder.prefill_draft",
        "ray_tpu.train.context.TrainContext.report",
        "ray_tpu.rllib.learner.Learner.update",
        # Podracer planes (round 17): the inference tier's coalesced
        # forward and the learner plane's device-resident minibatch step
        # both sit on the decoupled hot path.
        "ray_tpu.rllib.podracer.InferenceServer._flush",
        "ray_tpu.rllib.dqn.DQNLearner.update_device",
    }
)

# RL104: collective operations whose call ORDER must be rank-uniform.
# send/recv are excluded: P2P is rank-conditional by definition.
_COLLECTIVE_OPS = frozenset(
    {
        "allreduce",
        "all_reduce",
        "allgather",
        "all_gather",
        "reducescatter",
        "reduce_scatter",
        "psum",
        "psum_scatter",
        "broadcast",
        "barrier",
        "pmean",
        "pmax",
        "pmin",
        "ppermute",
    }
)
_RANKISH = ("rank", "slice", "leader")
_RL104_PATHS = ("ray_tpu/util/collective/", "ray_tpu/train/")
_RL104_FILES = ("ray_tpu/rllib/learner.py",)

_STEP_SHAPED = re.compile(r"(^|_)(step|train|update|apply)(_|$)|step$")

# --only group filters (satellite of the jaxlint round): named families
# that expand to rule-id sets, mirroring the `--only metrics` delegation.
RULE_GROUPS = {
    "jax": frozenset({"RL101", "RL102", "RL103", "RL104"}),
    "locks": frozenset({"RL105"}),
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""
    # Advisory findings are surfaced (and must still be pragma-justified
    # to keep the tree at zero unsuppressed) but never flip the exit code:
    # the RL103 missing-donation tier is a judgement call per jit.
    advisory: bool = False

    def format(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        adv = " [advisory]" if self.advisory else ""
        return f"{self.path}:{self.line}: {self.rule}{adv} {self.message}{tag}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "Finding":
        return cls(**d)


class FileCtx:
    """One parsed source file: tree, parent links, pragma table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._raylint_parent = node  # type: ignore[attr-defined]
        # line -> {"ids": [...], "reason": str, "comment_only": bool};
        # malformed pragmas land in pragma_errors as RL000 findings.
        self.pragmas: dict[int, dict] = {}
        self.pragma_errors: list[Finding] = []
        self._collect_pragmas()

    def _collect_pragmas(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            if "raylint" not in line:
                continue
            m = PRAGMA_RE.search(line)
            if m is None:
                if re.search(r"#\s*raylint\b", line):
                    self.pragma_errors.append(
                        Finding(
                            "RL000",
                            self.relpath,
                            i,
                            "unparseable raylint pragma (expected "
                            "'# raylint: disable=RLxxx -- reason')",
                        )
                    )
                continue
            ids = sorted(
                {t.strip() for t in m.group(1).split(",") if t.strip()}
            )
            reason = (m.group("reason") or "").strip()
            bad = [r for r in ids if r not in RULE_IDS]
            if bad:
                self.pragma_errors.append(
                    Finding(
                        "RL000",
                        self.relpath,
                        i,
                        f"pragma names unknown rule id(s) {sorted(bad)}",
                    )
                )
                continue
            if not reason:
                self.pragma_errors.append(
                    Finding(
                        "RL000",
                        self.relpath,
                        i,
                        "pragma is missing the required reason string "
                        "('# raylint: disable=RLxxx -- why this is safe')",
                    )
                )
                continue
            self.pragmas[i] = {
                "ids": ids,
                "reason": reason,
                "comment_only": line.lstrip().startswith("#"),
            }


def _suppression_for(
    pragmas: dict, rule: str, line: int
) -> Optional[str]:
    """Reason string if ``rule`` is suppressed at ``line``.

    A pragma applies to findings on its own line, or — when it sits on
    a comment-only line — to the first following non-comment line.
    """
    ent = pragmas.get(line)
    if ent and rule in ent["ids"]:
        return ent["reason"]
    prev = pragmas.get(line - 1)
    if prev and rule in prev["ids"] and prev["comment_only"]:
        return prev["reason"]
    return None


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_raylint_parent", None)


# -- rule engine --------------------------------------------------------------


class Rule:
    ID = "RL000"
    TITLE = "base rule"

    def check(self, ctx: FileCtx) -> list[Finding]:  # per-file
        return []

    def finalize(self, tree: "TreeCtx") -> list[Finding]:  # whole-tree
        return []


def _call_name(node: ast.Call) -> tuple[Optional[str], Optional[str]]:
    """(base, attr) for ``base.attr(...)`` calls, (None, name) for bare."""
    f = node.func
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else None
        return base, f.attr
    if isinstance(f, ast.Name):
        return None, f.id
    return None, None


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walk a module, tracking whether the nearest enclosing function scope
    is async. Nested sync defs/lambdas shadow the async scope (their bodies
    run wherever they are called, not necessarily on the loop)."""

    def __init__(self):
        self.async_depth: list[bool] = []

    @property
    def in_async(self) -> bool:
        return bool(self.async_depth) and self.async_depth[-1]

    def visit_AsyncFunctionDef(self, node):
        self.async_depth.append(True)
        self.generic_visit(node)
        self.async_depth.pop()

    def visit_FunctionDef(self, node):
        self.async_depth.append(False)
        self.generic_visit(node)
        self.async_depth.pop()

    def visit_Lambda(self, node):
        self.async_depth.append(False)
        self.generic_visit(node)
        self.async_depth.pop()


class BlockingInAsync(Rule):
    ID = "RL001"
    TITLE = "blocking call inside async def"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings: list[Finding] = []
        rule_id = self.ID
        relpath = ctx.relpath

        class V(_AsyncBodyVisitor):
            def visit_Call(self, node):
                if self.in_async:
                    msg = self._blocking(node)
                    if msg:
                        findings.append(
                            Finding(rule_id, relpath, node.lineno, msg)
                        )
                self.generic_visit(node)

            @staticmethod
            def _blocking(node: ast.Call) -> Optional[str]:
                base, attr = _call_name(node)
                if base == "time" and attr == "sleep":
                    return (
                        "time.sleep() blocks the event loop; "
                        "use `await asyncio.sleep()`"
                    )
                if base == "subprocess" and attr in _BLOCKING_SUBPROCESS:
                    return (
                        f"subprocess.{attr}() blocks the event loop; use "
                        "asyncio.create_subprocess_* or run_in_executor"
                    )
                if base == "os" and attr in ("system", "popen", "waitpid"):
                    return f"os.{attr}() blocks the event loop"
                if base == "socket" and attr in _BLOCKING_SOCKET:
                    return (
                        f"socket.{attr}() does blocking network I/O on "
                        "the event loop"
                    )
                if base is None and attr == "open" and isinstance(
                    node.func, ast.Name
                ):
                    return (
                        "open() does blocking file I/O on the event loop; "
                        "use run_in_executor for anything non-trivial"
                    )
                if (
                    attr == "result"
                    and isinstance(node.func, ast.Attribute)
                    and not node.args
                    and not node.keywords
                ):
                    if isinstance(parent(node), ast.Await):
                        return None
                    return (
                        "zero-arg .result() can block the loop on an "
                        "unfinished future; await it (or pragma if the "
                        "future is provably done here)"
                    )
                if (
                    attr == "acquire"
                    and isinstance(node.func, ast.Attribute)
                    and not node.args
                    and not any(
                        k.arg in ("timeout", "blocking")
                        for k in node.keywords
                    )
                ):
                    if isinstance(parent(node), ast.Await):
                        return None  # asyncio.Lock.acquire()
                    return (
                        ".acquire() without a timeout can block the event "
                        "loop indefinitely"
                    )
                return None

        V().visit(ctx.tree)
        return findings


class LockAcrossAwait(Rule):
    ID = "RL002"
    TITLE = "threading lock held across await"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(
                "lock" in _expr_tail(item.context_expr).lower()
                for item in node.items
            ):
                continue
            if _contains_await(node.body):
                findings.append(
                    Finding(
                        self.ID,
                        ctx.relpath,
                        node.lineno,
                        "sync `with ...lock:` body contains `await` — the "
                        "thread lock is held across a suspension point "
                        "(deadlock/race in the hybrid concurrency model); "
                        "release before awaiting or use asyncio.Lock with "
                        "`async with`",
                    )
                )
        return findings


def _expr_tail(e: ast.AST) -> str:
    """Trailing name segment of a context expression (``self._lock`` ->
    '_lock', ``lock.gen_rlock()`` -> 'gen_rlock')."""
    if isinstance(e, ast.Call):
        e = e.func
    if isinstance(e, ast.Attribute):
        return e.attr
    if isinstance(e, ast.Name):
        return e.id
    return ""


def _contains_await(body: list) -> bool:
    """Await anywhere in the statements, not crossing into nested defs."""
    stack = list(body)
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        if isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


class FireAndForgetTask(Rule):
    ID = "RL003"
    TITLE = "fire-and-forget task"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            _base, attr = _call_name(node)
            if attr not in ("ensure_future", "create_task"):
                continue
            # Discarded as a bare statement, OR as a lambda body — a
            # `call_soon(lambda: ensure_future(...))` / done-callback
            # lambda returns the task to a caller that drops it.
            if isinstance(parent(node), (ast.Expr, ast.Lambda)):
                findings.append(
                    Finding(
                        self.ID,
                        ctx.relpath,
                        node.lineno,
                        f"{attr}() result discarded — the task can be "
                        "GC'd mid-flight and its exception is silently "
                        "dropped; use ray_tpu.util.tasks.spawn (strong "
                        "ref + logged done-callback)",
                    )
                )
        return findings


class EnvVarHygiene(Rule):
    ID = "RL004"
    TITLE = "RAY_TPU_* env-var hygiene"

    CONFIG_RELPATH = os.path.join("ray_tpu", "core", "config.py")

    def finalize(self, tree: "TreeCtx") -> list[Finding]:
        knobs, bootstrap, knob_lines = tree.config_registry()
        out = []
        for facts in tree.facts.values():
            if facts["relpath"].replace(os.sep, "/").endswith(
                "core/config.py"
            ):
                continue
            for key, line in facts["env_reads"]:
                if not key.startswith(ENV_PREFIX):
                    continue
                field = key[len(ENV_PREFIX):].lower()
                if field in knobs:
                    out.append(
                        Finding(
                            self.ID,
                            facts["relpath"],
                            line,
                            f"direct read of config-knob env var {key}; use "
                            f"GLOBAL_CONFIG.{field} (env reads outside "
                            "core/config.py bypass the cluster-synced "
                            "config)",
                        )
                    )
                elif key not in bootstrap:
                    out.append(
                        Finding(
                            self.ID,
                            facts["relpath"],
                            line,
                            f"read of unregistered env var {key}: add it to "
                            "core/config.py (a Config knob, or "
                            "BOOTSTRAP_ENV_VARS for per-process bootstrap "
                            "interfaces) and document it in README.md",
                        )
                    )
        # README completeness: every knob and bootstrap var is external
        # interface and must be documented.
        readme = tree.readme_text()
        for field in sorted(knobs):
            env = ENV_PREFIX + field.upper()
            if env not in readme:
                out.append(
                    Finding(
                        self.ID,
                        self.CONFIG_RELPATH,
                        knob_lines.get(field, 1),
                        f"config knob {field} ({env}) is not documented "
                        "in README.md",
                    )
                )
        for env in sorted(bootstrap):
            if env not in readme:
                out.append(
                    Finding(
                        self.ID,
                        self.CONFIG_RELPATH,
                        knob_lines.get("__bootstrap__", 1),
                        f"bootstrap env var {env} is not documented in "
                        "README.md",
                    )
                )
        return out


def _env_read(node: ast.AST) -> tuple[Optional[str], int]:
    """(key, line) when ``node`` reads an environment variable with a
    constant key: os.environ.get/os.getenv/os.environ[...]."""
    if isinstance(node, ast.Call):
        base, attr = _call_name(node)
        is_environ_get = (
            attr == "get"
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "environ"
        ) or (
            attr == "get"
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "environ"
        )
        is_getenv = attr == "getenv" and (base in ("os", None))
        if (is_environ_get or is_getenv) and node.args:
            k = node.args[0]
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                return k.value, node.lineno
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        v = node.value
        if (
            isinstance(v, ast.Attribute)
            and v.attr == "environ"
            or isinstance(v, ast.Name)
            and v.id == "environ"
        ):
            k = node.slice
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                return k.value, node.lineno
    return None, 0


class RpcContract(Rule):
    ID = "RL005"
    TITLE = "RPC allowlist entries resolve to registered handlers"

    ALLOWLISTS = (
        "IDEMPOTENT_RPCS",
        "RPC_DEADLINE_EXEMPT",
        "_HEARTBEAT_RPCS",
        "_DATA_PLANE_RPCS",
        "_SLOW_RPCS",
    )

    def finalize(self, tree: "TreeCtx") -> list[Finding]:
        protocol = tree.facts.get("ray_tpu/core/protocol.py")
        if protocol is None:
            return []
        handlers = tree.handler_names()
        findings = []
        for listname, entry, lineno in protocol["allowlists"]:
            topic, dot, meth = entry.partition(".")
            resolved = dot and (
                f"_h_{meth}" in handlers
                or f"_h_{topic}_{meth}" in handlers
            )
            if not resolved:
                findings.append(
                    Finding(
                        self.ID,
                        protocol["relpath"],
                        lineno,
                        f"{listname} entry {entry!r} does not resolve "
                        "to any registered handler (_h_"
                        f"{meth or entry} / _h_{topic}_{meth}): stale "
                        "entry or renamed handler",
                    )
                )
        return findings


class SilentExcept(Rule):
    ID = "RL006"
    TITLE = "silently swallowed broad exception"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type):
                continue
            if _handler_acts(node.body):
                continue
            what = (
                "bare `except:`" if node.type is None
                else f"`except {ast.unparse(node.type)}`"
            )
            findings.append(
                Finding(
                    self.ID,
                    ctx.relpath,
                    node.lineno,
                    f"{what} swallows the error with no logging, "
                    "re-raise, or handling call — this can eat the typed "
                    "errors the robustness tier works to surface; log it, "
                    "narrow it, or pragma-justify it",
                )
            )
        return findings


def _is_broad(t: Optional[ast.AST]) -> bool:
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            return True
    return False


def _handler_acts(body: list) -> bool:
    """True when the handler body raises or calls anything — logging, a
    metrics bump, cleanup. A body of pass/continue/assignments is silent."""
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.Raise, ast.Call)):
                return True
    return False


# -- jax helpers (shared by RL101/RL102/RL103 and the facts extractor) --------


def _alias_base(base: Optional[str], imports: dict) -> Optional[str]:
    """Resolve an attribute base through the file's import aliases
    (``np`` -> 'numpy', ``jnp`` -> 'jax.numpy')."""
    if base is None:
        return None
    return imports.get(base, base)


def _collect_imports(tree: ast.AST) -> tuple[dict, dict]:
    """(imports, from_imports): local alias -> dotted module, and local
    name -> (dotted module, attr). Relative from-imports are left with a
    leading '.'-count prefix resolved later against the module path."""
    imports: dict[str, str] = {}
    from_imports: dict[str, tuple[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                # `import x.y as z` -> z: x.y; `import x.y` -> x: x (the
                # bound name is the top-level package).
                if a.asname:
                    imports[a.asname] = a.name
                else:
                    imports[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            mod = ("." * node.level) + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                from_imports[a.asname or a.name] = (mod, a.name)
    return imports, from_imports


def _is_jit_call(node: ast.Call, imports: dict) -> bool:
    """True for jax.jit(...) / jit(...) / pjit(...) / shard_map(...)."""
    base, attr = _call_name(node)
    rb = _alias_base(base, imports)
    if attr in ("jit", "pjit") and rb in (None, "jax", "jax.experimental.pjit"):
        return True
    if attr == "shard_map":
        return True
    return False


def _is_trace_call(node: ast.Call, imports: dict) -> bool:
    """True for transforms whose first argument becomes traced code:
    jit/shard_map plus jax.grad/value_and_grad/vmap/pmap/remat/checkpoint."""
    if _is_jit_call(node, imports):
        return True
    base, attr = _call_name(node)
    rb = _alias_base(base, imports)
    if rb == "jax" and attr in (
        "grad", "value_and_grad", "vmap", "pmap", "remat", "checkpoint"
    ):
        return True
    if base is None and attr == "value_and_grad":
        return True
    return False


def _is_partial_jit(node: ast.Call, imports: dict) -> bool:
    """functools.partial(jax.jit, ...) — the decorator spelling."""
    base, attr = _call_name(node)
    if attr != "partial" or _alias_base(base, imports) not in (
        None, "functools"
    ):
        return False
    return bool(
        node.args
        and isinstance(node.args[0], (ast.Attribute, ast.Name))
        and _is_jit_call(
            ast.Call(func=node.args[0], args=[], keywords=[]), imports
        )
    )


def _const_only(node: ast.AST) -> bool:
    """True when the expression is a constant / tuple-list of constants —
    a hashable, data-independent static_argnums value."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_const_only(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _const_only(node.operand)
    return False


def _enclosing(node: ast.AST, kinds, stop_at_def: bool = True):
    """Nearest ancestor of one of ``kinds``, not crossing def boundaries."""
    n = parent(node)
    while n is not None:
        if isinstance(n, kinds):
            return n
        if stop_at_def and isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return None
        n = parent(n)
    return None


class RecompilationHazard(Rule):
    ID = "RL102"
    TITLE = "jax recompilation hazard"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings = []
        imports, _ = _collect_imports(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not _is_jit_call(
                node, imports
            ):
                continue
            base, attr = _call_name(node)
            what = f"{base + '.' if base else ''}{attr}"
            loop = _enclosing(
                node, (ast.For, ast.While, ast.AsyncFor)
            )
            if loop is not None:
                findings.append(
                    Finding(
                        self.ID,
                        ctx.relpath,
                        node.lineno,
                        f"{what}(...) constructed inside a loop — every "
                        "iteration builds a fresh wrapper and retraces/"
                        "recompiles; hoist the jit out of the loop (or "
                        "cache it keyed on the static config)",
                    )
                )
            p = parent(node)
            if isinstance(p, ast.Call) and p.func is node:
                findings.append(
                    Finding(
                        self.ID,
                        ctx.relpath,
                        node.lineno,
                        f"{what}(fn)(...) wrapped-and-immediately-called — "
                        "the jit cache dies with the wrapper, so every "
                        "invocation retraces AND recompiles; bind the "
                        "jitted callable once and reuse it",
                    )
                )
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnames") and (
                    not _const_only(kw.value)
                ):
                    findings.append(
                        Finding(
                            self.ID,
                            ctx.relpath,
                            kw.value.lineno,
                            f"data-dependent {kw.arg} ({ast.unparse(kw.value)}) "
                            "— static args must be compile-time constants; "
                            "a value that varies per call means a silent "
                            "recompile per distinct value (or an unhashable-"
                            "type error at dispatch)",
                        )
                    )
        return findings


def _target_token(node: ast.AST) -> Optional[str]:
    """'x' for Name, 'self.x' for self-attributes — the donated-arg
    identity RL103 tracks."""
    if isinstance(node, ast.Name):
        return node.id
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return f"self.{node.attr}"
    return None


class DonationHygiene(Rule):
    ID = "RL103"
    TITLE = "jit donation hygiene"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings = []
        imports, _ = _collect_imports(ctx.tree)
        # 1) Which bound names carry donation? token -> set of donated
        #    positional indices (constant donate_argnums only).
        donate_bound: dict[str, tuple] = {}
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.value, ast.Call)
                and _is_jit_call(node.value, imports)
            ):
                continue
            token = _target_token(node.targets[0])
            if token is None:
                continue
            for kw in node.value.keywords:
                if kw.arg == "donate_argnums" and _const_only(kw.value):
                    positions = tuple(
                        e.value
                        for e in (
                            kw.value.elts
                            if isinstance(kw.value, (ast.Tuple, ast.List))
                            else [kw.value]
                        )
                        if isinstance(e, ast.Constant)
                    )
                    if positions:
                        donate_bound[token] = positions
        # 2) Advisory: step-shaped jit with no donation at all.
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and _is_jit_call(node, imports)
                and node.args
            ):
                continue
            fn_name = None
            a0 = node.args[0]
            if isinstance(a0, ast.Name):
                fn_name = a0.id
            elif isinstance(a0, ast.Attribute):
                fn_name = a0.attr
            if (
                fn_name
                and _STEP_SHAPED.search(fn_name)
                and not any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in node.keywords
                )
            ):
                findings.append(
                    Finding(
                        self.ID,
                        ctx.relpath,
                        node.lineno,
                        f"step-shaped jit of `{fn_name}` without donation — "
                        "donating the state argument(s) lets XLA alias "
                        "input/output buffers (halves HBM for the state); "
                        "donate, or pragma-document why not (e.g. CPU "
                        "harness: donated inputs block dispatch)",
                        advisory=True,
                    )
                )
        if not donate_bound:
            return findings
        # 3) Donated arg read after the jitted call, inside each function.
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            findings.extend(self._use_after_donate(ctx, fn, donate_bound))
        return findings

    def _use_after_donate(
        self, ctx: FileCtx, fn: ast.AST, donate_bound: dict
    ) -> list[Finding]:
        findings = []
        loads: dict[str, list] = {}
        stores: dict[str, list] = {}
        for n in ast.walk(fn):
            tok = _target_token(n)
            if tok is None:
                continue
            c = getattr(n, "ctx", None)
            if isinstance(c, ast.Store):
                stores.setdefault(tok, []).append(n.lineno)
            elif isinstance(c, ast.Load):
                loads.setdefault(tok, []).append(n.lineno)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            ftok = _target_token(node.func)
            if ftok not in donate_bound:
                continue
            for pos in donate_bound[ftok]:
                if pos >= len(node.args):
                    continue
                tok = _target_token(node.args[pos])
                if tok is None:
                    continue
                call_line = node.lineno
                # A multi-line call puts its own argument loads on lines
                # past lineno; only loads past the call's FULL span are
                # use-after-donate.
                call_end = getattr(node, "end_lineno", node.lineno)
                later_stores = sorted(
                    ln for ln in stores.get(tok, []) if ln >= call_line
                )
                kill = later_stores[0] if later_stores else None
                bad = [
                    ln
                    for ln in loads.get(tok, [])
                    if ln > call_end and (kill is None or ln < kill)
                ]
                # Loop bodies: a donated arg that is never re-bound in the
                # loop is stale on the next iteration even if the load line
                # precedes the call line.
                loop = _enclosing(node, (ast.For, ast.While, ast.AsyncFor))
                if loop is not None and not any(
                    loop.lineno <= ln <= max(
                        getattr(loop, "end_lineno", loop.lineno),
                        loop.lineno,
                    )
                    for ln in stores.get(tok, [])
                ):
                    bad.extend(
                        ln
                        for ln in loads.get(tok, [])
                        if loop.lineno <= ln <= getattr(
                            loop, "end_lineno", loop.lineno
                        )
                        and not (call_line <= ln <= call_end)
                    )
                for ln in sorted(set(bad)):
                    findings.append(
                        Finding(
                            self.ID,
                            ctx.relpath,
                            ln,
                            f"`{tok}` is donated to `{ftok}` (donate_argnums "
                            f"position {pos}, call at line {call_line}) and "
                            "read afterwards — a donated buffer is "
                            "invalidated by the call; rebind the result or "
                            "drop the donation",
                        )
                    )
        return findings


class CollectiveOrder(Rule):
    ID = "RL104"
    TITLE = "collective op under rank-conditional branch"

    def _in_scope(self, relpath: str) -> bool:
        rel = relpath.replace(os.sep, "/")
        return rel.startswith(_RL104_PATHS) or rel in _RL104_FILES

    def check(self, ctx: FileCtx) -> list[Finding]:
        if not self._in_scope(ctx.relpath):
            return []
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            _base, attr = _call_name(node)
            if attr not in _COLLECTIVE_OPS:
                continue
            cond = self._rankish_if(node)
            if cond is not None:
                findings.append(
                    Finding(
                        self.ID,
                        ctx.relpath,
                        node.lineno,
                        f"collective `{attr}` under the rank-/slice-"
                        f"conditional branch at line {cond.lineno} "
                        f"(`{ast.unparse(cond.test)[:60]}`) — ranks taking "
                        "different branches issue different collective "
                        "sequences and the group hangs; hoist the "
                        "collective out of the branch or pragma-document "
                        "the by-construction uniformity",
                    )
                )
        return findings

    @staticmethod
    def _rankish_if(node: ast.AST):
        """Nearest enclosing rank-conditional If/IfExp, else None."""
        n = parent(node)
        while n is not None:
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return None
            if isinstance(n, (ast.If, ast.IfExp)):
                for t in ast.walk(n.test):
                    name = None
                    if isinstance(t, ast.Name):
                        name = t.id
                    elif isinstance(t, ast.Attribute):
                        name = t.attr
                    if name and any(k in name.lower() for k in _RANKISH):
                        # If and IfExp both diverge: `allreduce(g) if
                        # rank == 0 else g` hangs ranks != 0 just the same.
                        return n
            n = parent(n)
        return None


# ==== facts layer ============================================================
# Everything the cross-file rules need, extracted once per file and
# serialized to .raylint_cache keyed on content hash: per-file findings,
# pragmas, env reads, handlers, the call graph (functions + call
# descriptors + jit bindings + traced roots), and the lock facts
# (definitions + acquisition regions).


def _module_dotted(relpath: str) -> str:
    rel = relpath.replace(os.sep, "/")
    if rel.endswith("/__init__.py"):
        rel = rel[: -len("/__init__.py")]
    elif rel.endswith(".py"):
        rel = rel[:-3]
    return rel.replace("/", ".")


def _resolve_relative(mod: str, own_module: str, is_pkg_init: bool) -> str:
    """Turn a '.'-prefixed from-import module into a dotted absolute."""
    if not mod.startswith("."):
        return mod
    level = len(mod) - len(mod.lstrip("."))
    rest = mod.lstrip(".")
    parts = own_module.split(".")
    # level 1 = own package; __init__ modules ARE their package.
    keep = len(parts) - (level - 1 if is_pkg_init else level)
    base = parts[:max(keep, 0)]
    return ".".join(base + ([rest] if rest else []))


def _expr_desc(e: ast.AST) -> Optional[list]:
    """Call/lock descriptor for an expression:
    ["name", n] | ["selfattr", a] | ["modattr", base, a] |
    ["objattr", selfattr, a] (self.X.a)."""
    if isinstance(e, ast.Name):
        return ["name", e.id]
    if isinstance(e, ast.Attribute):
        v = e.value
        if isinstance(v, ast.Name):
            if v.id in ("self", "cls"):
                return ["selfattr", e.attr]
            return ["modattr", v.id, e.attr]
        if (
            isinstance(v, ast.Attribute)
            and isinstance(v.value, ast.Name)
            and v.value.id in ("self", "cls")
        ):
            return ["objattr", v.attr, e.attr]
    return None


class _FactsWalker(ast.NodeVisitor):
    """One pass over a file's AST collecting the cross-file facts."""

    def __init__(self, ctx: FileCtx, module: str):
        self.ctx = ctx
        self.module = module
        self.imports, raw_from = _collect_imports(ctx.tree)
        is_init = ctx.relpath.replace(os.sep, "/").endswith("__init__.py")
        self.from_imports = {
            name: [_resolve_relative(mod, module, is_init), attr]
            for name, (mod, attr) in raw_from.items()
        }
        self.functions: dict[str, dict] = {}
        self.classes: dict[str, dict] = {}
        self.module_locks: dict[str, str] = {}
        self.module_jit: list[str] = []
        self.traced: list[dict] = []
        self.handlers: list[str] = []
        self.env_reads: list[list] = []
        self._scope: list[str] = []       # qualname parts
        self._fstack: list[dict] = []     # function recs
        self._cstack: list[str] = []      # class names
        self._wstack: list[dict] = []     # active lock regions

    # -- helpers -------------------------------------------------------------

    def _qual(self) -> str:
        return ".".join(self._scope)

    def _cur_class(self) -> Optional[str]:
        return self._cstack[-1] if self._cstack else None

    def _class_rec(self, name: str) -> dict:
        return self.classes.setdefault(
            name, {"bases": [], "itypes": {}, "locks": {}, "jit_attrs": []}
        )

    def _record_traced(self, desc: list) -> None:
        self.traced.append(
            {
                "desc": desc,
                "cls": self._cur_class(),
                "scope": self._qual() or None,
            }
        )

    def _maybe_traced_target(self, call: ast.Call) -> None:
        if not call.args:
            return
        desc = _expr_desc(call.args[0])
        if desc is not None:
            self._record_traced(desc)

    # -- scopes --------------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        rec = self._class_rec(node.name)
        for b in node.bases:
            d = _expr_desc(b)
            if d is not None:
                rec["bases"].append(d)
        self._scope.append(node.name)
        self._cstack.append(node.name)
        self.generic_visit(node)
        self._cstack.pop()
        self._scope.pop()

    def _visit_funcdef(self, node) -> None:
        if node.name.startswith("_h_"):
            self.handlers.append(node.name)
        self._scope.append(node.name)
        qual = self._qual()
        rec = {
            "qual": qual,
            "cls": self._cur_class(),
            "line": node.lineno,
            "calls": [],
            "sync": [],
            "scalar": [],
            "jit_local": [],
            "regions": [],
        }
        # A nested def is conservatively assumed callable from its
        # encloser (closure creation sits on the encloser's path).
        if self._fstack:
            self._fstack[-1]["calls"].append([["nested", qual], node.lineno])
        self.functions[qual] = rec
        # jit-ish decorators make the def traced AND jit-bound.
        for dec in node.decorator_list:
            traced = False
            if isinstance(dec, (ast.Attribute, ast.Name)):
                probe = ast.Call(func=dec, args=[], keywords=[])
                traced = _is_jit_call(probe, self.imports)
            elif isinstance(dec, ast.Call):
                traced = _is_jit_call(dec, self.imports) or _is_partial_jit(
                    dec, self.imports
                )
            if traced:
                self._record_traced(
                    ["name", node.name]
                    if not self._cur_class()
                    else ["selfattr", node.name]
                )
                if self._cur_class():
                    self._class_rec(self._cur_class())["jit_attrs"].append(
                        node.name
                    )
                else:
                    self.module_jit.append(node.name)
        self._fstack.append(rec)
        saved_w, self._wstack = self._wstack, []
        self.generic_visit(node)
        self._wstack = saved_w
        self._fstack.pop()
        self._scope.pop()

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    # -- statements ----------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1:
            self._handle_binding(node.targets[0], node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # `self._lock: threading.Lock = threading.Lock()` — annotated
        # definitions bind locks/jits exactly like plain assignments.
        if node.value is not None:
            self._handle_binding(node.target, node.value)
        self.generic_visit(node)

    def _handle_binding(self, target: ast.AST, value: ast.AST) -> None:
        if isinstance(value, ast.Call):
            tdesc = _expr_desc(target)
            vb, va = _call_name(value)
            rvb = _alias_base(vb, self.imports)
            if tdesc is not None:
                # lock definitions
                if rvb in (None, "threading") and va in ("Lock", "RLock"):
                    if tdesc[0] == "selfattr" and self._cur_class():
                        self._class_rec(self._cur_class())["locks"][
                            tdesc[1]
                        ] = va
                    elif tdesc[0] == "name" and not self._fstack:
                        self.module_locks[tdesc[1]] = va
                # jit bindings
                if _is_jit_call(value, self.imports):
                    if tdesc[0] == "selfattr" and self._cur_class():
                        self._class_rec(self._cur_class())[
                            "jit_attrs"
                        ].append(tdesc[1])
                    elif tdesc[0] == "name":
                        if self._fstack:
                            self._fstack[-1]["jit_local"].append(tdesc[1])
                        else:
                            self.module_jit.append(tdesc[1])
                # instance typing: self.X = ClassName(...) / mod.Class(...)
                if (
                    tdesc[0] == "selfattr"
                    and self._cur_class()
                    and va
                    and va[:1].isupper()
                ):
                    vdesc = _expr_desc(value.func)
                    if vdesc is not None:
                        self._class_rec(self._cur_class())["itypes"][
                            tdesc[1]
                        ] = vdesc

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def _visit_with(self, node) -> None:
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        regions = []
        for item in node.items:
            d = _expr_desc(item.context_expr)
            if d is None or not self._fstack:
                continue
            region = {"lock": d, "line": node.lineno, "calls": [], "locks": []}
            for outer in self._wstack:
                outer["locks"].append([d, node.lineno])
            self._fstack[-1]["regions"].append(region)
            self._wstack.append(region)
            regions.append(region)
        for stmt in node.body:
            self.visit(stmt)
        for _ in regions:
            self._wstack.pop()

    # -- expressions ---------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        key, line = _env_read(node)
        if key is not None:
            self.env_reads.append([key, line])
        base, attr = _call_name(node)
        rb = _alias_base(base, self.imports)
        if _is_trace_call(node, self.imports):
            self._maybe_traced_target(node)
        if self._fstack:
            rec = self._fstack[-1]
            desc = _expr_desc(node.func)
            if desc is not None:
                rec["calls"].append([desc, node.lineno])
                for region in self._wstack:
                    region["calls"].append([desc, node.lineno])
                # Explicit .acquire() counts as an acquisition event.
                if desc[0] in ("selfattr", "modattr", "objattr") and (
                    node.func.attr == "acquire"
                    if isinstance(node.func, ast.Attribute)
                    else False
                ):
                    inner = _expr_desc(node.func.value)
                    if inner is not None:
                        for outer in self._wstack:
                            outer["locks"].append([inner, node.lineno])
            # RL101 sync-site candidates.
            sync = None
            if attr == "device_get" and (
                rb == "jax"
                or (
                    base is None
                    and self.from_imports.get("device_get", [None])[0]
                    == "jax"
                )
            ):
                sync = ["device_get", node.lineno,
                        "jax.device_get forces device->host readback"]
            elif attr == "asarray" and rb == "numpy":
                sync = ["np_asarray", node.lineno,
                        "np.asarray forces device->host readback of a "
                        "device-resident value"]
            elif attr == "block_until_ready" and isinstance(
                node.func, ast.Attribute
            ):
                sync = ["block_until_ready", node.lineno,
                        ".block_until_ready() blocks the host on device "
                        "completion"]
            elif (
                attr == "item"
                and isinstance(node.func, ast.Attribute)
                and not node.args
                and not node.keywords
            ):
                sync = ["item", node.lineno,
                        ".item() forces device->host readback of a scalar"]
            if sync is not None:
                rec["sync"].append(sync)
            if (
                base is None
                and attr in ("float", "int", "bool")
                and len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)
            ):
                rec["scalar"].append([node.lineno, attr])
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        key, line = _env_read(node)
        if key is not None:
            self.env_reads.append([key, line])
        self.generic_visit(node)


def _config_registry_from_tree(tree: ast.AST) -> dict:
    """Knob fields / bootstrap env vars / lines, parsed statically from a
    core/config.py AST — raylint never imports the tree."""
    knobs: list[str] = []
    bootstrap: list[str] = []
    lines: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    knobs.append(stmt.target.id)
                    lines[stmt.target.id] = stmt.lineno
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "BOOTSTRAP_ENV_VARS"
        ):
            lines["__bootstrap__"] = node.lineno
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    bootstrap.append(c.value)
    return {"knobs": knobs, "bootstrap": bootstrap, "lines": lines}


def _allowlists_from_tree(tree: ast.AST) -> list:
    out = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id in RpcContract.ALLOWLISTS
        ):
            continue
        listname = node.targets[0].id
        for c in ast.walk(node.value):
            if isinstance(c, ast.Constant) and isinstance(c.value, str):
                out.append([listname, c.value, c.lineno])
    return out


def extract_facts(ctx: FileCtx) -> dict:
    """All per-file analysis results, as one JSON-serializable dict."""
    module = _module_dotted(ctx.relpath)
    walker = _FactsWalker(ctx, module)
    walker.visit(ctx.tree)
    findings: list[Finding] = []
    for rule in ALL_RULES:
        findings.extend(rule.check(ctx))
    rel = ctx.relpath.replace(os.sep, "/")
    facts = {
        "version": SCHEMA_VERSION,
        "relpath": ctx.relpath,
        "module": module,
        "pragmas": {str(k): v for k, v in ctx.pragmas.items()},
        "pragma_errors": [f.to_json() for f in ctx.pragma_errors],
        "findings": [f.to_json() for f in findings],
        "env_reads": walker.env_reads,
        "handlers": walker.handlers,
        "imports": walker.imports,
        "from_imports": walker.from_imports,
        "functions": walker.functions,
        "classes": walker.classes,
        "module_locks": walker.module_locks,
        "module_jit": walker.module_jit,
        "traced": walker.traced,
        "config": (
            _config_registry_from_tree(ctx.tree)
            if rel.endswith("core/config.py")
            else None
        ),
        "allowlists": (
            _allowlists_from_tree(ctx.tree)
            if rel.endswith("core/protocol.py")
            else None
        ),
    }
    return facts


# ==== cross-file analyses ====================================================


class _Resolver:
    """Name resolution over the facts layer: call descriptors ->
    (relpath, qualname) function nodes, lock descriptors -> lock ids."""

    _MAX_HOPS = 4  # from-import re-export chains (__init__ hops)

    def __init__(self, tree: "TreeCtx"):
        self.tree = tree
        self.by_module: dict[str, dict] = {}
        for facts in tree.facts.values():
            self.by_module[facts["module"]] = facts
        # lock id -> kind ("Lock"/"RLock")
        self.lock_defs: dict[str, str] = {}
        for facts in tree.facts.values():
            rel = facts["relpath"]
            for name, kind in facts["module_locks"].items():
                self.lock_defs[f"{rel}::{name}"] = kind
            for cls, crec in facts["classes"].items():
                for attr, kind in crec["locks"].items():
                    self.lock_defs[f"{rel}::{cls}.{attr}"] = kind

    # -- function resolution -------------------------------------------------

    def rec(self, nid: tuple) -> Optional[dict]:
        facts = self.tree.facts.get(nid[0])
        return facts["functions"].get(nid[1]) if facts else None

    def module_func(self, dotted: str, name: str, hops: int = 0):
        facts = self.by_module.get(dotted)
        if facts is None or hops > self._MAX_HOPS:
            return None
        if name in facts["functions"] and "." not in name:
            return (facts["relpath"], name)
        fi = facts["from_imports"].get(name)
        if fi is not None:
            return self.module_func(fi[0], fi[1], hops + 1)
        return None

    def find_class(self, dotted: str, name: str, hops: int = 0):
        facts = self.by_module.get(dotted)
        if facts is None or hops > self._MAX_HOPS:
            return None
        if name in facts["classes"]:
            return (facts["module"], name)
        fi = facts["from_imports"].get(name)
        if fi is not None:
            return self.find_class(fi[0], fi[1], hops + 1)
        return None

    def _class_desc(self, facts: dict, desc: list):
        """Resolve a class-reference descriptor to (module, class)."""
        if desc[0] == "name":
            return self.find_class(facts["module"], desc[1])
        if desc[0] == "modattr":
            dotted = facts["imports"].get(desc[1])
            if dotted is None:
                fi = facts["from_imports"].get(desc[1])
                if fi is not None:
                    dotted = f"{fi[0]}.{fi[1]}"
            if dotted is not None:
                return self.find_class(dotted, desc[2])
        return None

    def method_on_class(
        self, module: str, cls: str, attr: str, depth: int = 0
    ):
        if depth > self._MAX_HOPS:
            return None
        facts = self.by_module.get(module)
        if facts is None:
            return None
        crec = facts["classes"].get(cls)
        if crec is None:
            return None
        qual = f"{cls}.{attr}"
        if qual in facts["functions"]:
            return (facts["relpath"], qual)
        for bdesc in crec["bases"]:
            owner = self._class_desc(facts, bdesc)
            if owner is not None:
                hit = self.method_on_class(
                    owner[0], owner[1], attr, depth + 1
                )
                if hit is not None:
                    return hit
        return None

    def resolve_call(
        self, facts: dict, caller_qual: Optional[str],
        caller_cls: Optional[str], desc: list,
    ) -> list:
        kind = desc[0]
        if kind == "nested":
            return [(facts["relpath"], desc[1])]
        if kind == "name":
            n = desc[1]
            # enclosing-scope nested defs first, innermost out
            if caller_qual:
                parts = caller_qual.split(".")
                for i in range(len(parts), 0, -1):
                    q = ".".join(parts[:i] + [n])
                    if q in facts["functions"]:
                        return [(facts["relpath"], q)]
            if n in facts["functions"] and "." not in n:
                return [(facts["relpath"], n)]
            fi = facts["from_imports"].get(n)
            if fi is not None:
                hit = self.module_func(fi[0], fi[1], 1)
                return [hit] if hit else []
            return []
        if kind == "selfattr":
            if caller_cls is None:
                return []
            hit = self.method_on_class(facts["module"], caller_cls, desc[1])
            return [hit] if hit else []
        if kind == "modattr":
            m, a = desc[1], desc[2]
            dotted = facts["imports"].get(m)
            if dotted is None:
                fi = facts["from_imports"].get(m)
                if fi is not None:
                    dotted = f"{fi[0]}.{fi[1]}"
            if dotted is not None:
                hit = self.module_func(dotted, a)
                return [hit] if hit else []
            return []
        if kind == "objattr":
            if caller_cls is None:
                return []
            crec = facts["classes"].get(caller_cls, {})
            tdesc = crec.get("itypes", {}).get(desc[1])
            if tdesc is None:
                return []
            owner = self._class_desc(facts, tdesc)
            if owner is None:
                return []
            hit = self.method_on_class(owner[0], owner[1], desc[2])
            return [hit] if hit else []
        return []

    # -- lock resolution -----------------------------------------------------

    def resolve_lock(
        self, facts: dict, caller_cls: Optional[str], desc: list
    ) -> Optional[str]:
        kind = desc[0]
        if kind == "selfattr":
            if caller_cls is None:
                return None
            return self._lock_on_class(
                facts["module"], caller_cls, desc[1]
            )
        if kind == "name":
            n = desc[1]
            if n in facts["module_locks"]:
                return f"{facts['relpath']}::{n}"
            fi = facts["from_imports"].get(n)
            if fi is not None:
                other = self.by_module.get(fi[0])
                if other and fi[1] in other["module_locks"]:
                    return f"{other['relpath']}::{fi[1]}"
            return None
        if kind == "modattr":
            m, a = desc[1], desc[2]
            dotted = facts["imports"].get(m)
            if dotted is not None:
                other = self.by_module.get(dotted)
                if other and a in other["module_locks"]:
                    return f"{other['relpath']}::{a}"
                return None
            # `box._lock` where box is a local: unique same-module class
            # holding a lock attribute of this name.
            owners = [
                cls
                for cls, crec in facts["classes"].items()
                if a in crec["locks"]
            ]
            if len(owners) == 1:
                return f"{facts['relpath']}::{owners[0]}.{a}"
            return None
        if kind == "objattr":
            if caller_cls is None:
                return None
            crec = facts["classes"].get(caller_cls, {})
            tdesc = crec.get("itypes", {}).get(desc[1])
            if tdesc is None:
                return None
            owner = self._class_desc(facts, tdesc)
            if owner is None:
                return None
            return self._lock_on_class(owner[0], owner[1], desc[2])
        return None

    def _lock_on_class(
        self, module: str, cls: str, attr: str, depth: int = 0
    ) -> Optional[str]:
        if depth > self._MAX_HOPS:
            return None
        facts = self.by_module.get(module)
        if facts is None:
            return None
        crec = facts["classes"].get(cls)
        if crec is None:
            return None
        if attr in crec["locks"]:
            return f"{facts['relpath']}::{cls}.{attr}"
        for bdesc in crec["bases"]:
            owner = self._class_desc(facts, bdesc)
            if owner is not None:
                hit = self._lock_on_class(
                    owner[0], owner[1], attr, depth + 1
                )
                if hit is not None:
                    return hit
        return None


def _lock_short(lock_id: str) -> str:
    rel, _, name = lock_id.partition("::")
    return f"{os.path.basename(rel)}::{name}"


class HostSyncInDeviceHot(Rule):
    ID = "RL101"
    TITLE = "host-device sync in device-hot code"

    def finalize(self, tree: "TreeCtx") -> list[Finding]:
        res = tree.resolver()
        hot_roots: dict[tuple, str] = {}
        traced_roots: dict[tuple, str] = {}
        for rel in sorted(tree.facts):
            facts = tree.facts[rel]
            for t in facts["traced"]:
                for nid in res.resolve_call(
                    facts, t["scope"], t["cls"], t["desc"]
                ):
                    if res.rec(nid) is not None:
                        traced_roots.setdefault(nid, "is passed to jit/shard_map")
            for qual in sorted(facts["functions"]):
                rec = facts["functions"][qual]
                full = f"{facts['module']}.{qual}"
                if full in DEVICE_HOT_ENTRYPOINTS:
                    hot_roots.setdefault(
                        (rel, qual), "is a registered device-hot entrypoint"
                    )
                    continue
                cls_jit = set()
                if rec["cls"]:
                    cls_jit = set(
                        facts["classes"].get(rec["cls"], {}).get(
                            "jit_attrs", []
                        )
                    )
                local_jit = set(rec["jit_local"]) | set(facts["module_jit"])
                for cdesc, _line in rec["calls"]:
                    if (
                        cdesc[0] == "name" and cdesc[1] in local_jit
                    ) or (cdesc[0] == "selfattr" and cdesc[1] in cls_jit):
                        hot_roots.setdefault(
                            (rel, qual), "dispatches a jitted callable"
                        )
                        break
        hot, hot_parent = self._reach(tree, res, set(hot_roots))
        traced, traced_parent = self._reach(tree, res, set(traced_roots))
        findings = []
        for rel in sorted(tree.facts):
            facts = tree.facts[rel]
            for qual in sorted(facts["functions"]):
                nid = (rel, qual)
                rec = facts["functions"][qual]
                in_traced = nid in traced
                in_hot = nid in hot
                if not (in_hot or in_traced):
                    continue
                if in_traced:
                    via = self._via(
                        nid, traced_parent, traced_roots, "traced"
                    )
                else:
                    via = self._via(nid, hot_parent, hot_roots, "device-hot")
                for kind, line, detail in rec["sync"]:
                    findings.append(
                        Finding(
                            self.ID,
                            rel,
                            line,
                            f"{detail} in "
                            f"{'traced' if in_traced else 'device-hot'} "
                            f"`{qual}` ({via}) — move the readback off the "
                            "step path, batch it at a flush point, or "
                            "pragma-document the intended sync",
                        )
                    )
                if in_traced:
                    for line, name in rec["scalar"]:
                        findings.append(
                            Finding(
                                self.ID,
                                rel,
                                line,
                                f"{name}() on a traced value in `{qual}` "
                                f"({via}) — concretizes at trace time "
                                "(ConcretizationTypeError, or a silent "
                                "host sync + retrace per value)",
                            )
                        )
        return findings

    @staticmethod
    def _reach(tree, res, roots: set):
        parentmap: dict[tuple, Optional[tuple]] = {r: None for r in roots}
        stack = sorted(roots)
        seen = set(roots)
        while stack:
            nid = stack.pop()
            rec = res.rec(nid)
            if rec is None:
                continue
            facts = tree.facts[nid[0]]
            for cdesc, _line in rec["calls"]:
                for callee in res.resolve_call(
                    facts, rec["qual"], rec["cls"], cdesc
                ):
                    if callee not in seen and res.rec(callee) is not None:
                        seen.add(callee)
                        parentmap[callee] = nid
                        stack.append(callee)
        return seen, parentmap

    @staticmethod
    def _via(nid, parentmap, roots, label) -> str:
        chain = []
        cur = nid
        while cur is not None and len(chain) < 6:
            chain.append(cur)
            if cur in roots:
                break
            cur = parentmap.get(cur)
        root = chain[-1]
        why = roots.get(root, "a device-hot root")
        path = " <- ".join(q for _rel, q in chain)
        return f"{label} via {path}; `{root[1]}` {why}"


class LockOrderCycles(Rule):
    ID = "RL105"
    TITLE = "cross-file lock-order deadlock"

    def finalize(self, tree: "TreeCtx") -> list[Finding]:
        res = tree.resolver()
        # lockset(fn) = every lock the function may acquire, itself or
        # transitively; each lock carries one example witness chain.
        # Results computed while a call-graph cycle member is on-stack are
        # INCOMPLETE (the on-stack callee contributes {}); memoizing them
        # would permanently drop lock edges, so only clean results are
        # cached — tainted ones recompute per top-level query, which is
        # correct because each fresh query sees the full subtree.
        memo: dict[tuple, dict] = {}
        onstack: set = set()

        def lockset(nid: tuple) -> dict:
            return _lockset(nid)[0]

        def _lockset(nid: tuple) -> tuple:
            if nid in memo:
                return memo[nid], True
            if nid in onstack:
                return {}, False
            rec = res.rec(nid)
            if rec is None:
                return {}, True
            onstack.add(nid)
            facts = tree.facts[nid[0]]
            out: dict[str, list] = {}
            clean = True
            for region in rec["regions"]:
                lid = res.resolve_lock(facts, rec["cls"], region["lock"])
                if lid is not None and lid not in out:
                    out[lid] = [
                        f"{nid[0]}:{region['line']} `{rec['qual']}` takes "
                        f"{_lock_short(lid)}"
                    ]
            for cdesc, cline in rec["calls"]:
                for callee in res.resolve_call(
                    facts, rec["qual"], rec["cls"], cdesc
                ):
                    sub, sub_clean = _lockset(callee)
                    clean = clean and sub_clean
                    for lid, chain in sub.items():
                        if lid not in out:
                            out[lid] = [
                                f"{nid[0]}:{cline} `{rec['qual']}` -> "
                                f"`{callee[1]}`"
                            ] + chain
            onstack.discard(nid)
            if clean:
                memo[nid] = out
            return out, clean

        # Edges: lock M acquired (directly or through a call) while L held.
        edges: dict[tuple, dict] = {}

        def add_edge(L, M, site, chain):
            key = (L, M)
            if key not in edges:
                edges[key] = {"site": site, "chain": chain}

        findings: list[Finding] = []
        nodes_acquired: set = set()
        for rel in sorted(tree.facts):
            facts = tree.facts[rel]
            for qual in sorted(facts["functions"]):
                rec = facts["functions"][qual]
                for region in rec["regions"]:
                    L = res.resolve_lock(facts, rec["cls"], region["lock"])
                    if L is None:
                        continue
                    nodes_acquired.add(L)
                    owner_rel = L.partition("::")[0]
                    if owner_rel != rel:
                        findings.append(
                            Finding(
                                self.ID,
                                rel,
                                region["line"],
                                f"foreign lock {_lock_short(L)} (defined in "
                                f"{owner_rel}) acquired directly from "
                                f"`{qual}` — a private lock taken outside "
                                "its owning component makes lock order "
                                "impossible to reason about locally (the "
                                "deadlock-cycle precondition); add an "
                                "owner-side method that takes its own lock",
                            )
                        )
                    for mdesc, mline in region["locks"]:
                        M = res.resolve_lock(facts, rec["cls"], mdesc)
                        if M is None:
                            continue
                        nodes_acquired.add(M)
                        add_edge(
                            L, M, (rel, mline),
                            [
                                f"{rel}:{mline} `{qual}` takes "
                                f"{_lock_short(M)} while holding "
                                f"{_lock_short(L)}"
                            ],
                        )
                    for cdesc, cline in region["calls"]:
                        for callee in res.resolve_call(
                            facts, qual, rec["cls"], cdesc
                        ):
                            for M, chain in lockset(callee).items():
                                nodes_acquired.add(M)
                                add_edge(
                                    L, M, (rel, cline),
                                    [
                                        f"{rel}:{cline} `{qual}` (holding "
                                        f"{_lock_short(L)}) -> "
                                        f"`{callee[1]}`"
                                    ] + chain,
                                )
        # Self-deadlock: a non-reentrant Lock re-acquired while held.
        n_cycles = 0
        for (L, M), info in sorted(edges.items()):
            if L == M and res.lock_defs.get(L) == "Lock":
                n_cycles += 1
                findings.append(
                    Finding(
                        self.ID,
                        info["site"][0],
                        info["site"][1],
                        f"non-reentrant Lock {_lock_short(L)} acquired "
                        "while already held — same-instance re-entry "
                        "self-deadlocks (and cross-instance nesting of one "
                        "lock class has no defined order); witness: "
                        + " ; ".join(info["chain"]),
                    )
                )
        # AB/BA (and longer) cycles: SCCs of the lock digraph.
        adj: dict[str, list] = {}
        for (L, M) in edges:
            if L != M:
                adj.setdefault(L, []).append(M)
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            n_cycles += 1
            cyc = self._concrete_cycle(scc, adj)
            legs = []
            for a, b in zip(cyc, cyc[1:]):
                info = edges[(a, b)]
                legs.append(
                    f"{_lock_short(a)} -> {_lock_short(b)} "
                    f"[{' ; '.join(info['chain'])}]"
                )
            site = edges[(cyc[0], cyc[1])]["site"]
            findings.append(
                Finding(
                    self.ID,
                    site[0],
                    site[1],
                    "lock-order cycle "
                    + " -> ".join(_lock_short(x) for x in cyc)
                    + " — threads taking these locks in opposite orders "
                    "deadlock; establish one global order or release "
                    "before calling across the boundary. Witness paths: "
                    + " || ".join(legs),
                )
            )
        tree.lock_graph = {
            "nodes": len(nodes_acquired),
            "edges": sum(1 for (L, M) in edges if L != M),
            "cycles": n_cycles,
        }
        return findings

    @staticmethod
    def _concrete_cycle(scc: list, adj: dict) -> list:
        """A concrete cycle path a -> ... -> a inside one SCC (BFS)."""
        start = sorted(scc)[0]
        sset = set(scc)
        prev = {start: None}
        queue = [start]
        while queue:
            cur = queue.pop(0)
            for nxt in sorted(adj.get(cur, [])):
                if nxt == start:
                    seq = []
                    node = cur
                    while node is not None:
                        seq.append(node)
                        node = prev[node]
                    seq.reverse()  # [start, ..., cur]
                    return seq + [start]
                if nxt in sset and nxt not in prev:
                    prev[nxt] = cur
                    queue.append(nxt)
        return [start, start]


def _sccs(adj: dict) -> list:
    """Strongly connected components (iterative Tarjan)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set = set()
    stack: list = []
    out: list = []
    counter = [0]
    nodes = sorted(set(adj) | {m for ms in adj.values() for m in ms})

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, []))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, [])))))
                    advanced = True
                    break
                elif nxt in on:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                pnode = work[-1][0]
                low[pnode] = min(low[pnode], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


ALL_RULES: list[Rule] = [
    BlockingInAsync(),
    LockAcrossAwait(),
    FireAndForgetTask(),
    EnvVarHygiene(),
    RpcContract(),
    SilentExcept(),
    HostSyncInDeviceHot(),
    RecompilationHazard(),
    DonationHygiene(),
    CollectiveOrder(),
    LockOrderCycles(),
]
RULE_IDS = frozenset(r.ID for r in ALL_RULES) | {"RL000"}


# -- tree driver --------------------------------------------------------------


def _tool_salt() -> str:
    """Hash of this file's own source: editing any rule invalidates every
    cache entry without manual version bumps."""
    try:
        with open(os.path.abspath(__file__), "rb") as f:
            src = f.read()
    except OSError:
        src = b""
    return hashlib.sha256(SCHEMA_VERSION.encode() + src).hexdigest()[:16]


class FactsCache:
    """Content-addressed per-file facts under <repo>/.raylint_cache/."""

    def __init__(self, repo_root: str, enabled: bool = True):
        self.salt = _tool_salt()
        self.root = os.path.join(repo_root, CACHE_DIRNAME)
        # Entries live under a per-salt subdirectory: editing raylint
        # itself re-keys EVERY entry, so the old generation is dead
        # weight the moment the salt changes — prune() sweeps it.
        self.dir = os.path.join(self.root, self.salt)
        self.enabled = enabled
        self.hits = 0
        self.misses = 0
        self._touched: set = set()

    def key(self, relpath: str, source: str) -> str:
        # relpath is part of the key: two files with identical content
        # (empty __init__.py's) must not share an entry — facts embed the
        # relpath, and module identity drives the cross-file analyses.
        h = hashlib.sha256(self.salt.encode())
        h.update(relpath.encode("utf-8", "surrogatepass"))
        h.update(b"\x00")
        h.update(source.encode("utf-8", "surrogatepass"))
        return h.hexdigest()

    def get(self, relpath: str, source: str) -> Optional[dict]:
        if not self.enabled:
            return None
        name = self.key(relpath, source) + ".json"
        self._touched.add(name)
        path = os.path.join(self.dir, name)
        try:
            with open(path, "r", encoding="utf-8") as f:
                facts = json.load(f)
        except (OSError, ValueError):
            return None
        if (
            facts.get("version") != SCHEMA_VERSION
            or facts.get("relpath") != relpath
        ):
            return None
        self.hits += 1
        return facts

    def put(self, relpath: str, source: str, facts: dict) -> None:
        if not self.enabled:
            return
        self.misses += 1
        try:
            os.makedirs(self.dir, exist_ok=True)
            name = self.key(relpath, source) + ".json"
            self._touched.add(name)
            path = os.path.join(self.dir, name)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(facts, f)
            os.replace(tmp, path)
        except OSError:
            pass  # cache is best-effort; lint result is unaffected

    def prune(self) -> None:
        """Drop entries this run did not touch (superseded file versions)
        and every other-salt generation — a full-tree run touches exactly
        the live tree's entries, so the cache never outgrows the tree."""
        if not self.enabled:
            return
        try:
            for entry in os.listdir(self.root):
                full = os.path.join(self.root, entry)
                if entry != self.salt and os.path.isdir(full):
                    for fn in os.listdir(full):
                        try:
                            os.unlink(os.path.join(full, fn))
                        except OSError:
                            pass
                    try:
                        os.rmdir(full)
                    except OSError:
                        pass
            if os.path.isdir(self.dir):
                for fn in os.listdir(self.dir):
                    stale = fn not in self._touched  # superseded version
                    if not fn.endswith(".json"):
                        stale = True  # .tmp<pid> orphan of a killed put()
                    if stale:
                        try:
                            os.unlink(os.path.join(self.dir, fn))
                        except OSError:
                            pass
        except OSError:
            pass


class TreeCtx:
    """Whole-tree context: the per-file facts + cross-file registries."""

    def __init__(
        self,
        repo_root: Optional[str],
        scan_root: Optional[str] = None,
        use_cache: bool = True,
        facts_map: Optional[dict] = None,
    ):
        self.repo_root = repo_root
        self.facts: dict[str, dict] = {}
        self.lock_graph: Optional[dict] = None  # set by RL105.finalize
        self.cache: Optional[FactsCache] = None
        self._resolver: Optional[_Resolver] = None
        if facts_map is not None:
            self.facts = facts_map
            return
        self.scan_root = scan_root or os.path.join(repo_root, "ray_tpu")
        self.cache = FactsCache(repo_root, enabled=use_cache)
        self._load()

    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.scan_root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.repo_root).replace(
                    os.sep, "/"
                )
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                facts = self.cache.get(rel, src)
                if facts is None:
                    facts = extract_facts(FileCtx(path, rel, src))
                    self.cache.put(rel, src, facts)
                self.facts[rel] = facts
        self.cache.prune()

    def resolver(self) -> "_Resolver":
        """The cross-file name/lock resolver, built once per lint run and
        shared by every finalize() pass (RL101 + RL105)."""
        if self._resolver is None:
            self._resolver = _Resolver(self)
        return self._resolver

    def config_registry(self) -> tuple[set, set, dict]:
        """(knob field names, bootstrap env var names, field->line) parsed
        statically from core/config.py — raylint never imports the tree."""
        cfg = self.facts.get("ray_tpu/core/config.py")
        if cfg is None or cfg.get("config") is None:
            return set(), set(), {}
        reg = cfg["config"]
        return set(reg["knobs"]), set(reg["bootstrap"]), dict(reg["lines"])

    def handler_names(self) -> frozenset:
        out = set()
        for facts in self.facts.values():
            out.update(facts["handlers"])
        return frozenset(out)

    def readme_text(self) -> str:
        if not self.repo_root:
            return ""
        path = os.path.join(self.repo_root, "README.md")
        try:
            with open(path, "r", encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""


def _apply_suppressions(findings: list, facts_map: dict) -> None:
    tables: dict[str, dict] = {}
    for f in findings:
        facts = facts_map.get(f.path)
        if facts is None:
            continue
        table = tables.get(f.path)
        if table is None:
            table = {int(k): v for k, v in facts["pragmas"].items()}
            tables[f.path] = table
        reason = _suppression_for(table, f.rule, f.line)
        if reason is not None:
            f.suppressed = True
            f.reason = reason


def _run_rules(tree: TreeCtx, only: Optional[set]) -> list:
    findings: list[Finding] = []
    for rel in sorted(tree.facts):
        facts = tree.facts[rel]
        findings.extend(
            Finding.from_json(d) for d in facts["pragma_errors"]
        )
        for d in facts["findings"]:
            f = Finding.from_json(d)
            if only is None or f.rule in only:
                findings.append(f)
    for rule in ALL_RULES:
        if only is None or rule.ID in only:
            findings.extend(rule.finalize(tree))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _apply_suppressions(findings, tree.facts)
    return findings


def lint_tree_ex(
    repo_root: str = REPO_ROOT,
    scan_root: Optional[str] = None,
    only: Optional[set] = None,
    use_cache: bool = True,
) -> tuple[list, dict]:
    """Run the rule engine over the tree; returns (findings, meta) where
    meta carries the lock-graph summary and cache telemetry."""
    tree = TreeCtx(repo_root, scan_root, use_cache=use_cache)
    findings = _run_rules(tree, only)
    meta = {
        "lock_graph": tree.lock_graph,
        "cache": {
            "hits": tree.cache.hits if tree.cache else 0,
            "misses": tree.cache.misses if tree.cache else 0,
        },
    }
    return findings, meta


def lint_tree(
    repo_root: str = REPO_ROOT,
    scan_root: Optional[str] = None,
    only: Optional[set] = None,
    use_cache: bool = True,
) -> list:
    """Back-compat driver: findings only (callers filter ``.suppressed``)."""
    return lint_tree_ex(repo_root, scan_root, only, use_cache)[0]


def lint_text(
    source: str, relpath: str = "fixture.py", only: Optional[set] = None
) -> list:
    """Lint a source snippet as a single-file tree (fixture test hook).
    All rules run, including the cross-file analyses, against a tree
    containing only this file — RL004 resolves against an empty registry
    (every RAY_TPU_* read is unregistered), RL101 reachability and the
    RL105 lock graph see just this file's call graph."""
    ctx = FileCtx("<fixture>", relpath, source)
    facts = extract_facts(ctx)
    tree = TreeCtx(None, facts_map={relpath: facts})
    return _run_rules(tree, only)


def summarize(findings: Iterable[Finding]) -> dict:
    fs = list(findings)
    return {
        "total": len(fs),
        "suppressed": sum(1 for f in fs if f.suppressed),
        "unsuppressed": sum(1 for f in fs if not f.suppressed),
        "advisory": sum(1 for f in fs if f.advisory),
        "by_rule": {
            rid: sum(1 for f in fs if f.rule == rid)
            for rid in sorted({f.rule for f in fs})
        },
    }


def _gate_findings(findings: Iterable[Finding]) -> list:
    """The findings that flip the exit code: unsuppressed, non-advisory."""
    return [f for f in findings if not f.suppressed and not f.advisory]


def _git_changed_files(repo_root: str) -> Optional[set]:
    """Repo-relative paths changed vs HEAD (staged + unstaged + untracked);
    None when git is unavailable."""
    try:
        changed = subprocess.run(
            # --relative: paths relative to repo_root (not the git
            # toplevel) and scoped to it — findings carry root-relative
            # paths, and a vendored-subdir checkout must still match.
            ["git", "-C", repo_root, "diff", "--relative", "--name-only",
             "HEAD"],
            capture_output=True, text=True, timeout=30,
        )
        untracked = subprocess.run(
            ["git", "-C", repo_root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30,
        )
        if changed.returncode != 0 or untracked.returncode != 0:
            return None
        out = set()
        for blob in (changed.stdout, untracked.stdout):
            out.update(p.strip() for p in blob.splitlines() if p.strip())
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def _expand_only(spec: str, ap: argparse.ArgumentParser) -> set:
    only: set = set()
    for tok in (t.strip() for t in spec.split(",")):
        if not tok:
            continue
        if tok.lower() in RULE_GROUPS:
            only |= RULE_GROUPS[tok.lower()]
        elif tok in RULE_IDS:
            only.add(tok)
        else:
            ap.error(
                f"unknown rule id or group: {tok!r} "
                f"(groups: {sorted(RULE_GROUPS)}, ids: RLxxx)"
            )
    return only


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="raylint", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    ap.add_argument(
        "--only",
        default=None,
        help="comma-separated rule ids (e.g. RL003,RL006), a group "
        "('jax' = RL101-RL104, 'locks' = RL105), or 'metrics' to run "
        "the metrics-catalog lint (tools/metrics_lint.py)",
    )
    ap.add_argument(
        "--root",
        default=REPO_ROOT,
        help="repository root (default: the checkout containing this file)",
    )
    ap.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings",
    )
    ap.add_argument(
        "--changed-only",
        action="store_true",
        help="report only findings in files changed vs git HEAD "
        "(cross-file analysis still runs over the whole tree)",
    )
    ap.add_argument(
        "--no-cache",
        action="store_true",
        help="bypass the .raylint_cache/ per-file facts cache",
    )
    args = ap.parse_args(argv)

    if args.only and args.only.strip().lower() == "metrics":
        # One lint entry point: delegate to the metrics-catalog lint
        # (imports the instrumented layers, so it runs only on demand).
        sys.path.insert(0, args.root)
        from tools import metrics_lint

        return metrics_lint.main()

    only = _expand_only(args.only, ap) if args.only else None

    findings, meta = lint_tree_ex(
        repo_root=args.root, only=only, use_cache=not args.no_cache
    )
    if args.changed_only:
        changed = _git_changed_files(args.root)
        if changed is None:
            print(
                "raylint: --changed-only needs git; reporting full tree",
                file=sys.stderr,
            )
        elif "tools/raylint.py" in changed:
            # The tool itself changed: rule behavior may have shifted in
            # EVERY file, so the changed-file filter would green-light
            # findings full CI rejects. Report the whole tree.
            print(
                "raylint: tools/raylint.py changed; --changed-only "
                "reporting the full tree",
                file=sys.stderr,
            )
        else:
            # Keep (a) findings in changed files and (b) UNSUPPRESSED
            # findings from the cross-file rules wherever they anchor — a
            # local edit can break RL004/RL005/RL101/RL105 invariants in a
            # file you didn't touch (rename a handler, move a jit root),
            # and hiding those would green-light a commit full CI rejects.
            cross = {"RL004", "RL005", "RL101", "RL105"}
            findings = [
                f
                for f in findings
                if f.path in changed
                or (not f.suppressed and f.rule in cross)
            ]
    counts = summarize(findings)
    lg = meta["lock_graph"]  # None unless RL105 actually ran
    if args.json:
        payload = {
            **counts,
            "cache": meta["cache"],
            "findings": [f.to_json() for f in findings],
        }
        if lg is not None:
            payload["lock_graph"] = lg
        print(json.dumps(payload))
    else:
        for f in findings:
            if f.suppressed and not args.show_suppressed:
                continue
            print(f.format())
        summary = (
            f"raylint: {counts['unsuppressed']} unsuppressed, "
            f"{counts['suppressed']} suppressed finding(s)"
        )
        if lg is not None:
            summary += (
                f"; lock graph {lg['nodes']} locks / {lg['edges']} edges"
                f" / {lg['cycles']} cycle(s)"
            )
        print(summary)
    return 1 if _gate_findings(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
