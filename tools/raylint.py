"""raylint — AST-level concurrency & invariant lint for the ray_tpu runtime.

The runtime carries load-bearing invariants that exist only by convention:
a hybrid asyncio + ``threading.Lock`` concurrency model, RPC allowlists in
``core/protocol.py``, env-var kill switches, and a long tail of broad
``except Exception`` blocks. This tool machine-checks those properties the
way ``tools/metrics_lint.py`` checks the series catalog — CI-enforced via
``tests/test_raylint.py``, so every future PR holds them by construction.

Rule families
-------------
RL001  blocking call inside ``async def`` (``time.sleep``, blocking
       socket/subprocess/file I/O, zero-arg ``Future.result()``,
       ``Lock.acquire()`` without a timeout) — one blocked event loop
       stalls every collective behind it.
RL002  ``threading.Lock``/``RLock`` held across an ``await`` (a sync
       ``with ...lock:`` whose body awaits) — deadlock/race class in the
       hybrid concurrency model.
RL003  fire-and-forget task: ``asyncio.ensure_future``/``create_task``
       whose result is discarded (bare expression statement). Use
       ``ray_tpu.util.tasks.spawn`` — it strong-refs the task and logs
       non-cancelled exceptions instead of dropping them at GC time.
RL004  env-var hygiene: every ``RAY_TPU_*`` read outside
       ``core/config.py`` must be a registered bootstrap var
       (``config.BOOTSTRAP_ENV_VARS``); reads of config-knob env vars
       must go through ``GLOBAL_CONFIG``; every knob and bootstrap var
       must be documented in README.md.
RL005  RPC-contract consistency: every method name in the
       ``core/protocol.py`` allowlists (``IDEMPOTENT_RPCS``,
       ``RPC_DEADLINE_EXEMPT`` and the deadline-class sets) must resolve
       to a handler actually registered on an Endpoint (``_h_<meth>`` /
       ``_h_<topic>_<meth>`` convention).
RL006  silent exception swallowing: a bare/broad except whose body
       neither raises nor calls anything (no logging, no cleanup call)
       can eat exactly the typed errors the robustness tier surfaces.
RL000  malformed suppression pragma (unknown rule id or missing reason).

Suppression
-----------
``# raylint: disable=RL006 -- <reason>`` on the finding's line (or on a
comment-only line directly above it). The reason string is REQUIRED —
a pragma without one is itself a finding (RL000) and fails CI.

Run::

    python tools/raylint.py              # lint ray_tpu/, exit 1 on findings
    python tools/raylint.py --json       # machine-readable findings + counts
    python tools/raylint.py --only RL003,RL006
    python tools/raylint.py --only metrics   # the metrics-catalog lint
                                             # (tools/metrics_lint.py)

Adding a rule: subclass ``Rule``, set ``ID``/``TITLE``, implement
``check(ctx)`` (per-file) and/or ``finalize(tree_ctx)`` (whole-tree), and
append it to ``ALL_RULES``. Add the three fixtures (violating / clean /
pragma-suppressed) in tests/test_raylint.py and a row to the README table.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Iterable, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRAGMA_RE = re.compile(
    r"#\s*raylint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s+--\s*(?P<reason>.*\S))?\s*$"
)
ENV_PREFIX = "RAY_TPU_"

# Socket-module calls that actually block on the network. gethostname()
# and friends are local libc lookups and deliberately NOT listed.
_BLOCKING_SOCKET = {
    "create_connection",
    "getaddrinfo",
    "gethostbyname",
    "gethostbyname_ex",
    "gethostbyaddr",
    "getfqdn",
}
_BLOCKING_SUBPROCESS = {
    "run",
    "call",
    "check_call",
    "check_output",
    "getoutput",
    "getstatusoutput",
    "Popen",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def format(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{tag}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class FileCtx:
    """One parsed source file: tree, parent links, pragma table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._raylint_parent = node  # type: ignore[attr-defined]
        # line -> (frozenset of rule ids, reason); malformed pragmas land
        # in pragma_errors as RL000 findings.
        self.pragmas: dict[int, tuple[frozenset, str]] = {}
        self.pragma_errors: list[Finding] = []
        self._collect_pragmas()

    def _collect_pragmas(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            if "raylint" not in line:
                continue
            m = PRAGMA_RE.search(line)
            if m is None:
                if re.search(r"#\s*raylint\b", line):
                    self.pragma_errors.append(
                        Finding(
                            "RL000",
                            self.relpath,
                            i,
                            "unparseable raylint pragma (expected "
                            "'# raylint: disable=RLxxx -- reason')",
                        )
                    )
                continue
            ids = frozenset(
                t.strip() for t in m.group(1).split(",") if t.strip()
            )
            reason = (m.group("reason") or "").strip()
            bad = [r for r in ids if r not in RULE_IDS]
            if bad:
                self.pragma_errors.append(
                    Finding(
                        "RL000",
                        self.relpath,
                        i,
                        f"pragma names unknown rule id(s) {sorted(bad)}",
                    )
                )
                continue
            if not reason:
                self.pragma_errors.append(
                    Finding(
                        "RL000",
                        self.relpath,
                        i,
                        "pragma is missing the required reason string "
                        "('# raylint: disable=RLxxx -- why this is safe')",
                    )
                )
                continue
            self.pragmas[i] = (ids, reason)

    def suppression_for(self, rule: str, line: int) -> Optional[str]:
        """Reason string if ``rule`` is suppressed at ``line``.

        A pragma applies to findings on its own line, or — when it sits on
        a comment-only line — to the first following non-comment line.
        """
        ent = self.pragmas.get(line)
        if ent and rule in ent[0]:
            return ent[1]
        prev = line - 1
        if prev >= 1 and prev in self.pragmas:
            ids, reason = self.pragmas[prev]
            if rule in ids and self.lines[prev - 1].lstrip().startswith("#"):
                return reason
        return None


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_raylint_parent", None)


# -- rule engine --------------------------------------------------------------


class Rule:
    ID = "RL000"
    TITLE = "base rule"

    def check(self, ctx: FileCtx) -> list[Finding]:  # per-file
        return []

    def finalize(self, tree: "TreeCtx") -> list[Finding]:  # whole-tree
        return []


def _call_name(node: ast.Call) -> tuple[Optional[str], Optional[str]]:
    """(base, attr) for ``base.attr(...)`` calls, (None, name) for bare."""
    f = node.func
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else None
        return base, f.attr
    if isinstance(f, ast.Name):
        return None, f.id
    return None, None


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walk a module, tracking whether the nearest enclosing function scope
    is async. Nested sync defs/lambdas shadow the async scope (their bodies
    run wherever they are called, not necessarily on the loop)."""

    def __init__(self):
        self.async_depth: list[bool] = []

    @property
    def in_async(self) -> bool:
        return bool(self.async_depth) and self.async_depth[-1]

    def visit_AsyncFunctionDef(self, node):
        self.async_depth.append(True)
        self.generic_visit(node)
        self.async_depth.pop()

    def visit_FunctionDef(self, node):
        self.async_depth.append(False)
        self.generic_visit(node)
        self.async_depth.pop()

    def visit_Lambda(self, node):
        self.async_depth.append(False)
        self.generic_visit(node)
        self.async_depth.pop()


class BlockingInAsync(Rule):
    ID = "RL001"
    TITLE = "blocking call inside async def"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings: list[Finding] = []
        rule_id = self.ID
        relpath = ctx.relpath

        class V(_AsyncBodyVisitor):
            def visit_Call(self, node):
                if self.in_async:
                    msg = self._blocking(node)
                    if msg:
                        findings.append(
                            Finding(rule_id, relpath, node.lineno, msg)
                        )
                self.generic_visit(node)

            @staticmethod
            def _blocking(node: ast.Call) -> Optional[str]:
                base, attr = _call_name(node)
                if base == "time" and attr == "sleep":
                    return (
                        "time.sleep() blocks the event loop; "
                        "use `await asyncio.sleep()`"
                    )
                if base == "subprocess" and attr in _BLOCKING_SUBPROCESS:
                    return (
                        f"subprocess.{attr}() blocks the event loop; use "
                        "asyncio.create_subprocess_* or run_in_executor"
                    )
                if base == "os" and attr in ("system", "popen", "waitpid"):
                    return f"os.{attr}() blocks the event loop"
                if base == "socket" and attr in _BLOCKING_SOCKET:
                    return (
                        f"socket.{attr}() does blocking network I/O on "
                        "the event loop"
                    )
                if base is None and attr == "open" and isinstance(
                    node.func, ast.Name
                ):
                    return (
                        "open() does blocking file I/O on the event loop; "
                        "use run_in_executor for anything non-trivial"
                    )
                if (
                    attr == "result"
                    and isinstance(node.func, ast.Attribute)
                    and not node.args
                    and not node.keywords
                ):
                    if isinstance(parent(node), ast.Await):
                        return None
                    return (
                        "zero-arg .result() can block the loop on an "
                        "unfinished future; await it (or pragma if the "
                        "future is provably done here)"
                    )
                if (
                    attr == "acquire"
                    and isinstance(node.func, ast.Attribute)
                    and not node.args
                    and not any(
                        k.arg in ("timeout", "blocking")
                        for k in node.keywords
                    )
                ):
                    if isinstance(parent(node), ast.Await):
                        return None  # asyncio.Lock.acquire()
                    return (
                        ".acquire() without a timeout can block the event "
                        "loop indefinitely"
                    )
                return None

        V().visit(ctx.tree)
        return findings


class LockAcrossAwait(Rule):
    ID = "RL002"
    TITLE = "threading lock held across await"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(
                "lock" in _expr_tail(item.context_expr).lower()
                for item in node.items
            ):
                continue
            if _contains_await(node.body):
                findings.append(
                    Finding(
                        self.ID,
                        ctx.relpath,
                        node.lineno,
                        "sync `with ...lock:` body contains `await` — the "
                        "thread lock is held across a suspension point "
                        "(deadlock/race in the hybrid concurrency model); "
                        "release before awaiting or use asyncio.Lock with "
                        "`async with`",
                    )
                )
        return findings


def _expr_tail(e: ast.AST) -> str:
    """Trailing name segment of a context expression (``self._lock`` ->
    '_lock', ``lock.gen_rlock()`` -> 'gen_rlock')."""
    if isinstance(e, ast.Call):
        e = e.func
    if isinstance(e, ast.Attribute):
        return e.attr
    if isinstance(e, ast.Name):
        return e.id
    return ""


def _contains_await(body: list) -> bool:
    """Await anywhere in the statements, not crossing into nested defs."""
    stack = list(body)
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        if isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


class FireAndForgetTask(Rule):
    ID = "RL003"
    TITLE = "fire-and-forget task"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            _base, attr = _call_name(node)
            if attr not in ("ensure_future", "create_task"):
                continue
            # Discarded as a bare statement, OR as a lambda body — a
            # `call_soon(lambda: ensure_future(...))` / done-callback
            # lambda returns the task to a caller that drops it.
            if isinstance(parent(node), (ast.Expr, ast.Lambda)):
                findings.append(
                    Finding(
                        self.ID,
                        ctx.relpath,
                        node.lineno,
                        f"{attr}() result discarded — the task can be "
                        "GC'd mid-flight and its exception is silently "
                        "dropped; use ray_tpu.util.tasks.spawn (strong "
                        "ref + logged done-callback)",
                    )
                )
        return findings


class EnvVarHygiene(Rule):
    ID = "RL004"
    TITLE = "RAY_TPU_* env-var hygiene"

    CONFIG_RELPATH = os.path.join("ray_tpu", "core", "config.py")

    def check(self, ctx: FileCtx) -> list[Finding]:
        if ctx.relpath.replace(os.sep, "/").endswith("core/config.py"):
            return []
        findings = []
        for node in ast.walk(ctx.tree):
            key, line = _env_read(node)
            if key is None or not key.startswith(ENV_PREFIX):
                continue
            findings.append(
                Finding(self.ID, ctx.relpath, line, key)
            )  # resolved in finalize against the config registry
        return findings

    def finalize(self, tree: "TreeCtx") -> list[Finding]:
        knobs, bootstrap, knob_lines = tree.config_registry()
        out = []
        for f in tree.pending.pop(self.ID, []):
            key = f.message
            field = key[len(ENV_PREFIX):].lower()
            if field in knobs:
                f.message = (
                    f"direct read of config-knob env var {key}; use "
                    f"GLOBAL_CONFIG.{field} (env reads outside "
                    "core/config.py bypass the cluster-synced config)"
                )
                out.append(f)
            elif key in bootstrap:
                continue
            else:
                f.message = (
                    f"read of unregistered env var {key}: add it to "
                    "core/config.py (a Config knob, or "
                    "BOOTSTRAP_ENV_VARS for per-process bootstrap "
                    "interfaces) and document it in README.md"
                )
                out.append(f)
        # README completeness: every knob and bootstrap var is external
        # interface and must be documented.
        readme = tree.readme_text()
        for field in sorted(knobs):
            env = ENV_PREFIX + field.upper()
            if env not in readme:
                out.append(
                    Finding(
                        self.ID,
                        self.CONFIG_RELPATH,
                        knob_lines.get(field, 1),
                        f"config knob {field} ({env}) is not documented "
                        "in README.md",
                    )
                )
        for env in sorted(bootstrap):
            if env not in readme:
                out.append(
                    Finding(
                        self.ID,
                        self.CONFIG_RELPATH,
                        knob_lines.get("__bootstrap__", 1),
                        f"bootstrap env var {env} is not documented in "
                        "README.md",
                    )
                )
        return out


def _env_read(node: ast.AST) -> tuple[Optional[str], int]:
    """(key, line) when ``node`` reads an environment variable with a
    constant key: os.environ.get/os.getenv/os.environ[...]."""
    if isinstance(node, ast.Call):
        base, attr = _call_name(node)
        is_environ_get = (
            attr == "get"
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "environ"
        ) or (
            attr == "get"
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "environ"
        )
        is_getenv = attr == "getenv" and (base in ("os", None))
        if (is_environ_get or is_getenv) and node.args:
            k = node.args[0]
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                return k.value, node.lineno
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        v = node.value
        if (
            isinstance(v, ast.Attribute)
            and v.attr == "environ"
            or isinstance(v, ast.Name)
            and v.id == "environ"
        ):
            k = node.slice
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                return k.value, node.lineno
    return None, 0


class RpcContract(Rule):
    ID = "RL005"
    TITLE = "RPC allowlist entries resolve to registered handlers"

    ALLOWLISTS = (
        "IDEMPOTENT_RPCS",
        "RPC_DEADLINE_EXEMPT",
        "_HEARTBEAT_RPCS",
        "_DATA_PLANE_RPCS",
        "_SLOW_RPCS",
    )

    def finalize(self, tree: "TreeCtx") -> list[Finding]:
        protocol = tree.file("ray_tpu/core/protocol.py")
        if protocol is None:
            return []
        handlers = tree.handler_names()
        findings = []
        for node in ast.walk(protocol.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in self.ALLOWLISTS
            ):
                continue
            listname = node.targets[0].id
            for c in ast.walk(node.value):
                if not (
                    isinstance(c, ast.Constant) and isinstance(c.value, str)
                ):
                    continue
                entry = c.value
                topic, dot, meth = entry.partition(".")
                resolved = dot and (
                    f"_h_{meth}" in handlers
                    or f"_h_{topic}_{meth}" in handlers
                )
                if not resolved:
                    findings.append(
                        Finding(
                            self.ID,
                            protocol.relpath,
                            c.lineno,
                            f"{listname} entry {entry!r} does not resolve "
                            "to any registered handler (_h_"
                            f"{meth or entry} / _h_{topic}_{meth}): stale "
                            "entry or renamed handler",
                        )
                    )
        return findings


class SilentExcept(Rule):
    ID = "RL006"
    TITLE = "silently swallowed broad exception"

    def check(self, ctx: FileCtx) -> list[Finding]:
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type):
                continue
            if _handler_acts(node.body):
                continue
            what = (
                "bare `except:`" if node.type is None
                else f"`except {ast.unparse(node.type)}`"
            )
            findings.append(
                Finding(
                    self.ID,
                    ctx.relpath,
                    node.lineno,
                    f"{what} swallows the error with no logging, "
                    "re-raise, or handling call — this can eat the typed "
                    "errors the robustness tier works to surface; log it, "
                    "narrow it, or pragma-justify it",
                )
            )
        return findings


def _is_broad(t: Optional[ast.AST]) -> bool:
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            return True
    return False


def _handler_acts(body: list) -> bool:
    """True when the handler body raises or calls anything — logging, a
    metrics bump, cleanup. A body of pass/continue/assignments is silent."""
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.Raise, ast.Call)):
                return True
    return False


ALL_RULES: list[Rule] = [
    BlockingInAsync(),
    LockAcrossAwait(),
    FireAndForgetTask(),
    EnvVarHygiene(),
    RpcContract(),
    SilentExcept(),
]
RULE_IDS = frozenset(r.ID for r in ALL_RULES) | {"RL000"}


# -- tree driver --------------------------------------------------------------


class TreeCtx:
    """Whole-tree context: parsed files + cross-file registries."""

    def __init__(self, repo_root: str, scan_root: Optional[str] = None):
        self.repo_root = repo_root
        self.scan_root = scan_root or os.path.join(repo_root, "ray_tpu")
        self.files: dict[str, FileCtx] = {}
        # rule id -> findings parked by check() for finalize() resolution
        self.pending: dict[str, list[Finding]] = {}
        self._load()

    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.scan_root):
            dirnames[:] = [
                d for d in dirnames if d != "__pycache__"
            ]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.repo_root).replace(
                    os.sep, "/"
                )
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                self.files[rel] = FileCtx(path, rel, src)

    def file(self, relpath: str) -> Optional[FileCtx]:
        return self.files.get(relpath)

    def handler_names(self) -> frozenset:
        out = set()
        for ctx in self.files.values():
            for n in ast.walk(ctx.tree):
                if isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and n.name.startswith("_h_"):
                    out.add(n.name)
        return frozenset(out)

    def config_registry(self) -> tuple[set, set, dict]:
        """(knob field names, bootstrap env var names, field->line) parsed
        statically from core/config.py — raylint never imports the tree."""
        knobs: set[str] = set()
        bootstrap: set[str] = set()
        lines: dict[str, int] = {}
        cfg = self.file("ray_tpu/core/config.py")
        if cfg is None:
            return knobs, bootstrap, lines
        for node in ast.walk(cfg.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Config":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name
                    ):
                        knobs.add(stmt.target.id)
                        lines[stmt.target.id] = stmt.lineno
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "BOOTSTRAP_ENV_VARS"
            ):
                lines["__bootstrap__"] = node.lineno
                for c in ast.walk(node.value):
                    if isinstance(c, ast.Constant) and isinstance(
                        c.value, str
                    ):
                        bootstrap.add(c.value)
        return knobs, bootstrap, lines

    def readme_text(self) -> str:
        path = os.path.join(self.repo_root, "README.md")
        try:
            with open(path, "r", encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""


def _apply_suppressions(
    findings: list[Finding], files: dict[str, FileCtx]
) -> None:
    for f in findings:
        ctx = files.get(f.path)
        if ctx is None:
            continue
        reason = ctx.suppression_for(f.rule, f.line)
        if reason is not None:
            f.suppressed = True
            f.reason = reason


def lint_tree(
    repo_root: str = REPO_ROOT,
    scan_root: Optional[str] = None,
    only: Optional[set] = None,
) -> list[Finding]:
    """Run the rule engine over the tree; returns ALL findings (callers
    filter on ``.suppressed``)."""
    tree = TreeCtx(repo_root, scan_root)
    rules = [r for r in ALL_RULES if only is None or r.ID in only]
    findings: list[Finding] = []
    for ctx in tree.files.values():
        findings.extend(ctx.pragma_errors)
        for rule in rules:
            got = rule.check(ctx)
            if isinstance(rule, EnvVarHygiene):
                tree.pending.setdefault(rule.ID, []).extend(got)
            else:
                findings.extend(got)
    for rule in rules:
        findings.extend(rule.finalize(tree))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _apply_suppressions(findings, tree.files)
    return findings


def lint_text(
    source: str, relpath: str = "fixture.py", only: Optional[set] = None
) -> list[Finding]:
    """Lint a source snippet with the per-file rules (fixture test hook).
    Cross-file resolution (RL004 registry, RL005 handlers) needs
    ``lint_tree`` over a real tree."""
    ctx = FileCtx("<fixture>", relpath, source)
    rules = [r for r in ALL_RULES if only is None or r.ID in only]
    findings = list(ctx.pragma_errors)
    for rule in rules:
        got = rule.check(ctx)
        if isinstance(rule, EnvVarHygiene):
            # Fixture mode: resolve against an empty registry — every
            # RAY_TPU_* read is "unregistered".
            for f in got:
                f.message = f"read of unregistered env var {f.message}"
            findings.extend(got)
        else:
            findings.extend(got)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _apply_suppressions(findings, {relpath: ctx})
    return findings


def summarize(findings: Iterable[Finding]) -> dict:
    fs = list(findings)
    return {
        "total": len(fs),
        "suppressed": sum(1 for f in fs if f.suppressed),
        "unsuppressed": sum(1 for f in fs if not f.suppressed),
        "by_rule": {
            rid: sum(1 for f in fs if f.rule == rid)
            for rid in sorted({f.rule for f in fs})
        },
    }


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="raylint", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    ap.add_argument(
        "--only",
        default=None,
        help="comma-separated rule ids (e.g. RL003,RL006), or 'metrics' "
        "to run the metrics-catalog lint (tools/metrics_lint.py)",
    )
    ap.add_argument(
        "--root",
        default=REPO_ROOT,
        help="repository root (default: the checkout containing this file)",
    )
    ap.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings",
    )
    args = ap.parse_args(argv)

    if args.only and args.only.strip().lower() == "metrics":
        # One lint entry point: delegate to the metrics-catalog lint
        # (imports the instrumented layers, so it runs only on demand).
        sys.path.insert(0, args.root)
        from tools import metrics_lint

        return metrics_lint.main()

    only = None
    if args.only:
        only = {t.strip() for t in args.only.split(",") if t.strip()}
        unknown = only - RULE_IDS
        if unknown:
            ap.error(f"unknown rule id(s): {sorted(unknown)}")

    findings = lint_tree(repo_root=args.root, only=only)
    counts = summarize(findings)
    if args.json:
        print(
            json.dumps(
                {**counts, "findings": [f.to_json() for f in findings]}
            )
        )
    else:
        for f in findings:
            if f.suppressed and not args.show_suppressed:
                continue
            print(f.format())
        print(
            f"raylint: {counts['unsuppressed']} unsuppressed, "
            f"{counts['suppressed']} suppressed finding(s)"
        )
    return 1 if counts["unsuppressed"] else 0


if __name__ == "__main__":
    sys.exit(main())
