"""Same-session A/B of the host-free train-step tier (PERF.md round-13).

Runs ``tools/ray_perf.py --quick --train-only`` alternately with the
overlap tier ON (HEAD defaults: device-resident metrics in the pipelined
ring + device-prefetched input) and OFF (``--no-async-dispatch`` — the
WHOLE synchronous loop: device->host readback inside every report() AND
host-passthrough input, since default-depth prefetch follows the same
kill switch) on the SAME commit, interleaved so ambient box load hits
both arms equally (the round-3 lesson). The delta is the combined
readback+staging overlap, not readback alone. Watch:

    train_step_overlap          steps/s — the headline
    train_step_host_blocked_ms  consumer-thread stalls per step (metric
                                readback + obtaining the next batch); the
                                OFF arm syncs on the step it just
                                dispatched and then runs the loader with
                                the device idle, the ON arm waits only on
                                ring eviction (a step ~depth back) with
                                the loader hidden inside that wait
    train_prefetch_misses       input-staging underruns, ON arm only (the
                                OFF arm has no staging thread); nonzero
                                means the host data path is the
                                bottleneck, not the step

    python tools/ab_train_overlap.py [--rounds 3] [--full]

The interleaved-median machinery is shared with tools/ab_coalesce.py.
bench.py records the same pair per round as the ``train_overlap`` BENCH
record (like ``data_plane`` / ``serve_llm``).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ab_coalesce import ab_main  # noqa: E402 — shared harness


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    return ab_main(
        "--no-async-dispatch", "train-overlap", base_flags=("--train-only",)
    )


if __name__ == "__main__":
    sys.exit(main())
