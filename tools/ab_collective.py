"""Same-session A/B of the hierarchical collective tier (PERF.md round-11).

Runs tools/ray_perf.py alternately with the hierarchical + quantized
collectives ON (HEAD defaults) and OFF on the SAME commit, interleaved so
ambient box load hits both arms equally (the round-3 lesson). Two arms:

    --arm hierarchical   ON vs --no-hierarchical (flat one-ring baseline —
                         the strategy A/B; the 1-slice row must stay at
                         parity, it never takes the hierarchical path)
    --arm quantized      ON vs --no-quantized (hierarchical both sides,
                         fp32 DCN leg as baseline — isolates the codec;
                         read collective_dcn_bytes_ratio for the ~4x
                         wire-byte reduction)

    python tools/ab_collective.py [--arm hierarchical|quantized]
                                  [--rounds 3] [--full]

The interleaved-median machinery is shared with tools/ab_coalesce.py.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ab_coalesce import interleaved_ab  # noqa: E402 — shared harness

_ARMS = {
    "hierarchical": "--no-hierarchical",
    "quantized": "--no-quantized",
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--arm", choices=sorted(_ARMS), default="hierarchical",
        help="which kill switch the OFF arm uses",
    )
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument(
        "--full", action="store_true", help="full (not --quick) perf runs"
    )
    args = ap.parse_args()
    interleaved_ab(
        _ARMS[args.arm], f"collective-{args.arm}", args.rounds, args.full
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
