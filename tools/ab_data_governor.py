"""Same-session A/B of the memory-governed streaming data plane (PERF.md
round 18).

Runs ``tools/ray_perf.py --data-only`` alternately with the governor ON
(HEAD defaults) and OFF (``--no-data-governor``: the pre-governor
submission loop, byte-identical to the round-17 executor) on the SAME
commit, interleaved so ambient box load hits both arms equally (the
round-3 lesson). The workload is an out-of-core map pipeline: the object
store is capped 4x below the dataset, so the arms CANNOT both stay
bounded. Watch:

    data_pipeline_rows_per_s  throughput — the governed arm should win or
                              tie (spill-to-disk round trips are pure tax)
    data_peak_store_frac      governed: <= data_store_high_frac; OFF: at
                              the cap (the store saved itself by spilling)
    data_store_spills         governed: 0; OFF: > 0 — THE invariant
    data_throttle_events      governed arm only: the governor actually
                              arbitrated

    python tools/ab_data_governor.py [--rounds 3] [--full]

The interleaved-median machinery is shared with tools/ab_coalesce.py;
bench.py records the same pair per round as the ``data_governor`` BENCH
record.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ab_coalesce import interleaved_ab  # noqa: E402 — shared machinery


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument(
        "--full", action="store_true", help="full (not --quick) perf runs"
    )
    args = ap.parse_args()
    interleaved_ab(
        "--no-data-governor",
        "data-governor",
        args.rounds,
        args.full,
        base_flags=("--data-only",),
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
