"""Same-session A/B of the runtime telemetry tier.

Runs tools/ray_perf.py alternately with instrumentation ON (HEAD
defaults) and OFF (--no-metrics, i.e. RAY_TPU_METRICS_ENABLED=0) on the
SAME commit, interleaved so ambient box load hits both arms equally.
Prints per-metric medians and the on/off ratio — the acceptance gate is
tasks_sync and the actor-call rows staying within noise of 1.0
(PERF.md round-7).

    python tools/ab_metrics.py [--rounds 3] [--full]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ab_coalesce import ab_main  # noqa: E402 — shared interleaved harness


def main() -> int:
    return ab_main("--no-metrics", "metrics")


if __name__ == "__main__":
    sys.exit(main())
