"""Control-plane ceiling probe: where does the driver core actually go?

Round-4 verdict #8: PERF.md claims the single driver core is the
tasks_async bottleneck — this tool tests that claim instead of asserting
it. (The other suggested experiment — disjoint cgroup cpu quotas to
emulate two cores — is impossible here: nproc == 1, there is no second
core to carve out.)

Method: run the ray_perf tasks_async workload while wall-sampling every
thread of the DRIVER process (the GCS and the endpoint/event loops are
threads of this process; only worker executors are separate processes),
then attribute non-idle samples to buckets:

  serialization  pickle/cloudpickle/serialization.py dumps+loads
  eventloop      asyncio machinery + protocol framing + socket transport
  control-plane  core_worker/node/gcs/scheduler bookkeeping
  other          everything else (workload fn, numpy, interpreter misc)

Prints one JSON line; PERF.md records the conclusion.

Caveat: wall sampling on a timesharing core counts runnable-but-
preempted frames as on-CPU, so the split is approximate — but the
question is whether serialization+eventloop DOMINATE, and a dominance
signal survives that noise.
"""

from __future__ import annotations

import json
import threading
import time

import ray_tpu
from ray_tpu.util.profiling import sample_collapsed_stacks

BUCKETS = (
    ("serialization", (
        "/pickle.py", "cloudpickle", "serialization.py", "_Pickler",
    )),
    ("eventloop", (
        "/asyncio/", "protocol.py", "selectors.py", "/socket.py",
        "struct.py", "ssl.py",
    )),
    ("control-plane", (
        "core_worker.py", "node.py", "gcs.py", "scheduler.py",
        "object_store.py", "ids.py",
    )),
)


def classify(stack: str) -> str:
    # Leaf-most wins: walk frames from the leaf inward so a pickle call
    # made by core_worker counts as serialization, not bookkeeping.
    for frame in reversed(stack.split(";")):
        for name, needles in BUCKETS:
            if any(n in frame for n in needles):
                return name
    return "other"


def main() -> None:
    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote
    def small():
        return b"ok"

    # Warm the worker pool / code paths.
    ray_tpu.get([small.remote() for _ in range(100)])

    stop = threading.Event()
    reqs = {"n": 0}

    def drive():
        while not stop.is_set():
            ray_tpu.get([small.remote() for _ in range(100)])
            reqs["n"] += 100

    t0 = time.perf_counter()
    driver = threading.Thread(target=drive, daemon=True)
    driver.start()
    prof = sample_collapsed_stacks(duration_s=12.0, interval_s=0.005)
    stop.set()
    driver.join(timeout=30)
    elapsed = time.perf_counter() - t0

    totals: dict[str, int] = {}
    for stack, n in prof["stacks"].items():
        totals[classify(stack)] = totals.get(classify(stack), 0) + n
    busy = sum(totals.values())
    shares = {
        k: round(v / busy, 4) for k, v in sorted(
            totals.items(), key=lambda kv: -kv[1]
        )
    } if busy else {}
    top = sorted(prof["stacks"].items(), key=lambda kv: -kv[1])[:8]
    print(json.dumps({
        "metric": "tasks_async_ceiling_probe",
        "throughput_per_s": round(reqs["n"] / elapsed, 1),
        "busy_samples": busy,
        "total_sample_rounds": prof["samples"],
        "shares": shares,
        "pickle_plus_eventloop": round(
            (totals.get("serialization", 0) + totals.get("eventloop", 0))
            / busy, 4,
        ) if busy else None,
        "top_stacks": [
            {"n": n, "leaf": s.split(";")[-1]} for s, n in top
        ],
    }))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
