"""Control-plane microbenchmarks (reference: python/ray/_private/ray_perf.py).

Measures task/actor/object throughput of the ray_tpu runtime on one machine
and prints one line per metric. Run:

    python tools/ray_perf.py [--quick]

Results are checked into PERF.md next to BASELINE.md's reference numbers.
NOTE: the dev box has ONE physical core shared by driver + GCS + node +
workers; the reference numbers were taken on an m5.16xlarge (64 vCPU) head,
so absolute comparisons carry a large machine handicap.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

import ray_tpu


def timeit(name, fn, multiplier=1, warmup=1, min_s=2.0, max_iters=50):
    for _ in range(warmup):
        fn()
    start = time.perf_counter()
    iters = 0
    while True:
        fn()
        iters += 1
        elapsed = time.perf_counter() - start
        if elapsed > min_s or iters >= max_iters:
            break
    rate = multiplier * iters / elapsed
    print(f"{name}: {rate:,.1f} /s", flush=True)
    return name, rate


@ray_tpu.remote
def tiny():
    return b"ok"


@ray_tpu.remote
class Sink:
    def ping(self):
        return b"ok"

    def with_arg(self, x):
        return b"ok"

    async def aping(self):
        return b"ok"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--no-coalesce",
        action="store_true",
        help="kill switch: one-write-per-frame transport, unbatched "
        "lease/submission paths (the A/B baseline for PERF.md round-6)",
    )
    ap.add_argument(
        "--no-metrics",
        action="store_true",
        help="kill switch: disable all runtime telemetry (equivalent to "
        "RAY_TPU_METRICS_ENABLED=0) — the A/B baseline proving the "
        "instrumentation tax stays within the 5%% budget",
    )
    ap.add_argument(
        "--no-scatter-gather",
        action="store_true",
        help="kill switch: in-band frame pickling + join-based flush "
        "(the A/B baseline for the PERF.md round-8 data plane)",
    )
    ap.add_argument(
        "--data-plane-only",
        action="store_true",
        help="run only the large-object rows (bench.py rides this for "
        "the BENCH_r* data-plane record)",
    )
    ap.add_argument(
        "--no-hierarchical",
        action="store_true",
        help="kill switch: flat one-ring collectives (equivalent to "
        "RAY_TPU_HIERARCHICAL_COLLECTIVES=0) — the A/B baseline for the "
        "PERF.md round-11 hierarchical-collective tier",
    )
    ap.add_argument(
        "--no-quantized",
        action="store_true",
        help="keep the hierarchical structure but ship the DCN leg at "
        "full precision (no block-int8 codec) — isolates the "
        "quantization arm of the round-11 A/B",
    )
    ap.add_argument(
        "--faults",
        metavar="SEED:SPEC",
        help="enable the fault-injection plane for the whole run "
        "(RAY_TPU_FAULTS syntax; includes the node.preempt rule — a "
        "seeded graceful-drain notice) — the chaos-overhead arm of the "
        "robustness A/B; the default arm (injector off) must stay "
        "within noise of the pre-robustness numbers",
    )
    args = ap.parse_args()
    if args.faults:
        from ray_tpu.core import faults as _faults

        # Spawned worker processes re-import faults and read the env var;
        # without this, worker-side fault sites silently never fire.
        os.environ["RAY_TPU_FAULTS"] = args.faults
        _faults.install(_faults.parse_env(args.faults))
    batch = 20 if args.quick else 100
    min_s = 0.5 if args.quick else 2.0

    if (
        args.no_coalesce
        or args.no_metrics
        or args.no_scatter_gather
        or args.no_hierarchical
        or args.no_quantized
    ):
        from ray_tpu.core.config import GLOBAL_CONFIG

        # Before init: the head ships this config to every node/worker.
        if args.no_coalesce:
            GLOBAL_CONFIG.rpc_coalesce_enabled = False
        if args.no_metrics:
            GLOBAL_CONFIG.metrics_enabled = False
        if args.no_scatter_gather:
            GLOBAL_CONFIG.rpc_scatter_gather_enabled = False
        if args.no_hierarchical:
            GLOBAL_CONFIG.hierarchical_collectives = False
        if args.no_quantized:
            GLOBAL_CONFIG.collective_quantize_dcn = False

    ray_tpu.init(num_cpus=16)
    results = {}

    def record(name, fn, multiplier=1):
        n, rate = timeit(name, fn, multiplier, min_s=min_s)
        results[n] = rate

    # -- large objects (round-8 data plane) ----------------------------------
    # put_large: driver put through the shm single-copy path. get_large:
    # a BORROWER (actor-side) get of a driver-owned inline object — the
    # leg where the value actually rides RPC frames, so the scatter-gather
    # A/B shows here. actor_array_args: multi-MB array args on pipelined
    # actor calls (args always ride the push frame, at any size).
    from ray_tpu.core.config import GLOBAL_CONFIG as _CFG

    large = np.zeros(8 * 1024 * 1024, dtype=np.uint8)  # 8 MB
    mb = large.nbytes / 1e6

    def put_large():
        ref = ray_tpu.put(large)
        del ref

    n, rate = timeit("put_large", put_large, 1, min_s=min_s, max_iters=30)
    results[n] = round(rate * mb, 2)
    print(f"  -> {results[n]:.1f} MB/s", flush=True)

    @ray_tpu.remote
    class _DataSink:
        def checksum(self, x):
            return int(x[0]) + int(x[-1])

        def fetch(self, ref):
            return int(ray_tpu.get(ref[0])[0])

    dsink = _DataSink.remote()
    ray_tpu.get(dsink.checksum.remote(np.zeros(8, dtype=np.uint8)))

    # Owner-side inline storage for the borrower-get row: bump the inline
    # cap (driver-side decision only) so the 8 MB value is served from the
    # owner's memory store over RPC instead of the shm file plane.
    old_inline = _CFG.max_inline_object_bytes
    _CFG.max_inline_object_bytes = large.nbytes + 1
    try:
        inline_ref = ray_tpu.put(large)
    finally:
        _CFG.max_inline_object_bytes = old_inline

    def get_large():
        ray_tpu.get(dsink.fetch.remote([inline_ref]))

    n, rate = timeit("get_large", get_large, 1, min_s=min_s, max_iters=30)
    results[n] = round(rate * mb, 2)
    print(f"  -> {results[n]:.1f} MB/s", flush=True)

    def actor_array_args():
        ray_tpu.get(
            [dsink.checksum.remote(large) for _ in range(4)]
        )

    n, rate = timeit(
        "actor_array_args", actor_array_args, 4, min_s=min_s, max_iters=20
    )
    results[n] = round(rate * mb, 2)
    print(f"  -> {results[n]:.1f} MB/s", flush=True)

    if args.data_plane_only:
        print(json.dumps(results), flush=True)
        ray_tpu.shutdown()
        return 0

    # -- objects -------------------------------------------------------------
    small = b"x" * 1024

    def put_small():
        for _ in range(batch):
            ray_tpu.put(small)

    record("single_client_put_calls_1kb", put_small, batch)

    ref_small = ray_tpu.put(small)

    def get_small():
        for _ in range(batch):
            ray_tpu.get(ref_small)

    record("single_client_get_calls_1kb", get_small, batch)

    big = np.zeros(64 * 1024 * 1024, dtype=np.uint8)  # 64 MB through shm

    def put_big():
        ref = ray_tpu.put(big)
        del ref

    n, rate = timeit(
        "single_client_put_gigabytes", put_big, 1, min_s=min_s, max_iters=20
    )
    results[n] = rate * big.nbytes / 1e9
    print(f"  -> {results[n]:.2f} GB/s", flush=True)

    # -- tasks ---------------------------------------------------------------
    def tasks_sync():
        for _ in range(batch):
            ray_tpu.get(tiny.remote())

    record("single_client_tasks_sync", tasks_sync, batch)

    def tasks_async():
        ray_tpu.get([tiny.remote() for _ in range(batch * 5)])

    record("single_client_tasks_async", tasks_async, batch * 5)

    # -- actors --------------------------------------------------------------
    sink = Sink.remote()
    ray_tpu.get(sink.ping.remote())

    def actor_sync():
        for _ in range(batch):
            ray_tpu.get(sink.ping.remote())

    record("1_1_actor_calls_sync", actor_sync, batch)

    def actor_async():
        ray_tpu.get([sink.ping.remote() for _ in range(batch * 5)])

    record("1_1_actor_calls_async", actor_async, batch * 5)

    def actor_with_arg():
        ray_tpu.get([sink.with_arg.remote(small) for _ in range(batch * 2)])

    record("1_1_actor_calls_with_arg_async", actor_with_arg, batch * 2)

    asink = Sink.options(max_concurrency=8).remote()
    ray_tpu.get(asink.aping.remote())

    def async_actor_async():
        ray_tpu.get([asink.aping.remote() for _ in range(batch * 5)])

    record("1_1_async_actor_calls_async", async_actor_async, batch * 5)

    # n:n — 4 actors, submissions interleaved from one driver (our driver is
    # one process; the reference uses n driver processes).
    sinks = [Sink.remote() for _ in range(4)]
    ray_tpu.get([s.ping.remote() for s in sinks])

    def n_n_async():
        refs = []
        for _ in range(batch * 2):
            for s in sinks:
                refs.append(s.ping.remote())
        ray_tpu.get(refs)

    record("n_n_actor_calls_async", n_n_async, batch * 2 * len(sinks))

    # -- collectives (round-11 hierarchical + quantized DCN) -----------------
    # Two allreduce rows over real member-actor gangs on the coordinator
    # data plane: a 2-slice group (slice identities passed explicitly, so
    # auto strategy picks hierarchical unless --no-hierarchical) and a
    # 1-slice group (always flat — the parity row: hierarchical selection
    # must not touch it). Bytes ride MB/s like the data-plane rows; the
    # dcn byte counters from rank 0's process give the quantization ratio.

    @ray_tpu.remote(num_cpus=0)
    class _CollMember:
        def __init__(self, world, rank, group, slice_name):
            from ray_tpu.util import collective as col

            self._col = col
            self._group = group
            self._comm = col.init_collective_group(
                world, rank, backend="cpu", group_name=group,
                timeout_s=120.0, slice_name=slice_name,
            )

        def strategy(self):
            return self._comm.backend

        def allreduce(self, n):
            t = np.ones(n, np.float32)
            out = self._col.allreduce(t, group_name=self._group)
            return float(np.asarray(out)[0])

        def dcn_bytes(self):
            from ray_tpu.util.metrics import registry

            out = {"pre": 0.0, "post": 0.0}
            for name, _tags, value in registry().snapshot()["points"]:
                if name == "raytpu_collective_dcn_bytes_pre_total":
                    out["pre"] = float(value)
                elif name == "raytpu_collective_dcn_bytes_post_total":
                    out["post"] = float(value)
            return out

        def destroy(self):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(self._group)
            return True

    n_elems = 256 * 1024  # 1 MiB fp32 per rank per op
    coll_mb = n_elems * 4 / 1e6
    world = 4
    for row, slices in (
        ("collective_allreduce_2slice", ["s0", "s0", "s1", "s1"]),
        ("collective_allreduce_1slice", ["s0", "s0", "s0", "s0"]),
    ):
        members = [
            _CollMember.remote(world, r, row, slices[r])
            for r in range(world)
        ]
        strat = ray_tpu.get(
            [m.strategy.remote() for m in members], timeout=120
        )[0]

        def coll_op(ms=members):
            ray_tpu.get(
                [m.allreduce.remote(n_elems) for m in ms], timeout=120
            )

        n, rate = timeit(row, coll_op, 1, min_s=min_s, max_iters=30)
        results[n] = round(rate * coll_mb, 2)
        print(f"  -> {results[n]:.1f} MB/s ({strat})", flush=True)
        if row == "collective_allreduce_2slice":
            b = ray_tpu.get(members[0].dcn_bytes.remote(), timeout=60)
            if b["post"]:
                results["collective_dcn_bytes_ratio"] = round(
                    b["pre"] / b["post"], 3
                )
                print(
                    f"  dcn bytes: {b['pre']:.0f} pre / {b['post']:.0f} "
                    f"post = {results['collective_dcn_bytes_ratio']}x",
                    flush=True,
                )
        # Members destroy first (each tears down the hierarchical subgroup
        # coordinators it owns — killing them outright would leak those
        # actors into the rest of the timed run), then the driver reaps
        # any parent state left behind.
        try:
            ray_tpu.get([m.destroy.remote() for m in members], timeout=60)
        except Exception:
            pass
        from ray_tpu.util import collective as _col

        _col.destroy_collective_group(row)
        for m in members:
            ray_tpu.kill(m)

    # Transport counters: the strace-free syscall-reduction view
    # (PERF.md round-6 A/B rides these).
    from ray_tpu.core import api as _api

    t = _api.transport_stats()
    if t:
        results["transport_frames_sent"] = t["frames_sent"]
        results["transport_writes"] = t["writes"]
        results["transport_frames_per_write"] = round(
            t["frames_per_write"], 3
        )
        results["transport_drains_skipped"] = t["drains_skipped"]
        print(
            f"transport: {t['frames_sent']} frames / {t['writes']} writes "
            f"= {t['frames_per_write']:.2f} frames/write "
            f"(max {t['max_frames_per_write']}, drains awaited "
            f"{t['drains']}, skipped {t['drains_skipped']})",
            flush=True,
        )

    print(json.dumps(results), flush=True)
    ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
