"""Control-plane microbenchmarks (reference: python/ray/_private/ray_perf.py).

Measures task/actor/object throughput of the ray_tpu runtime on one machine
and prints one line per metric. Run:

    python tools/ray_perf.py [--quick]

Results are checked into PERF.md next to BASELINE.md's reference numbers.
NOTE: the dev box has ONE physical core shared by driver + GCS + node +
workers; the reference numbers were taken on an m5.16xlarge (64 vCPU) head,
so absolute comparisons carry a large machine handicap.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

import ray_tpu


def timeit(name, fn, multiplier=1, warmup=1, min_s=2.0, max_iters=50):
    for _ in range(warmup):
        fn()
    start = time.perf_counter()
    iters = 0
    while True:
        fn()
        iters += 1
        elapsed = time.perf_counter() - start
        if elapsed > min_s or iters >= max_iters:
            break
    rate = multiplier * iters / elapsed
    print(f"{name}: {rate:,.1f} /s", flush=True)
    return name, rate


@ray_tpu.remote
def tiny():
    return b"ok"


@ray_tpu.remote
class Sink:
    def ping(self):
        return b"ok"

    def with_arg(self, x):
        return b"ok"

    async def aping(self):
        return b"ok"


def _p99_ms(samples: list) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return round(s[min(len(s) - 1, int(0.99 * len(s)))] * 1e3, 2)


def _hist_snapshot(name: str) -> dict:
    """Cumulative bucket counts (le -> count, summed across processes)
    of one merged-cluster histogram, from the Prometheus exposition.
    Engine-side phase deltas come from diffing two of these — client
    timings on a contended box carry scheduler noise the engine's own
    step clock does not."""
    from ray_tpu.util.state.api import cluster_metrics_text

    out: dict = {}
    for line in cluster_metrics_text().splitlines():
        if not line.startswith(name + "_bucket"):
            continue
        try:
            le = line.split('le="', 1)[1].split('"', 1)[0]
            out[le] = out.get(le, 0.0) + float(line.rsplit(None, 1)[1])
        except (IndexError, ValueError):
            continue
    return out


def _hist_frac_above(before: dict, after: dict, boundary: str) -> float:
    """Fraction of NEW samples (between two snapshots) above ``boundary``
    seconds; -1 when the window saw no samples."""
    d = {le: after.get(le, 0.0) - before.get(le, 0.0) for le in after}
    total = d.get("+Inf", 0.0)
    if total <= 0:
        return -1.0
    return round((total - d.get(boundary, 0.0)) / total, 4)


def _serve_llm_rows(
    results: dict,
    no_chunked_prefill: bool,
    quick: bool,
    no_disagg: bool = False,
    no_spec_decode: bool = False,
):
    """Cache-aware LLM serving rows (PERF.md round-12): two tiny-model
    replicas behind the serve router, streaming clients from driver
    threads. Two traffic mixes:

      serve_llm_shared_prefix — 3 long shared system prompts x unique
        suffixes at high concurrency: prefix-affinity routing converges
        each prompt family onto the replica that pooled it (tok/s + p99
        TTFT vs --no-prefix-routing).
      serve_llm_mixed_len — long prompts interleaved with short in-flight
        decoders: chunked prefill bounds the decoders' p99 ITL (vs
        --no-chunked-prefill).
    """
    import concurrent.futures

    from ray_tpu import serve
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.llm.config import LLMConfig
    from ray_tpu.llm.serve_llm import build_openai_app
    from ray_tpu.models.gpt2 import GPT2Config

    # Sized so prefill is a real cost on CPU (the TPU-serving regime the
    # A/B models): a cold ~900-token prompt costs several decode steps,
    # so a missed cache reuse / an unchunked prefill stall is visible.
    # The prompt families share a 260-char boilerplate header then
    # DIVERGE — the pre-round-12 px: affinity (first 256 chars) cannot
    # tell them apart, block digests can — and the pool budget holds
    # only 2 of the 3 families per replica, so the families must
    # PARTITION across replicas to all stay warm.
    # Faster digest repair for the benchmark: one ~900-token request on
    # this box takes ~0.5 s, so the default 2 s staleness window lets a
    # single pool-churn event misroute several follow-ups; 0.75 s keeps
    # the table within ~1-2 requests of reality (documented knob — a
    # real deployment with ms-scale requests would RAISE it instead).
    GLOBAL_CONFIG.prefix_route_staleness_s = min(
        GLOBAL_CONFIG.prefix_route_staleness_s, 0.75
    )
    model = GPT2Config.tiny(n_layer=3, d_model=256, n_head=4, max_seq=1024)
    cfg = LLMConfig(
        model_config=model,
        max_slots=4,
        max_seq=1024,
        prefill_buckets=(32, 128, 1024),
        num_kv_blocks=420,
        prefix_chunk=32,
        max_prefix_cache_tokens=2048,
        prefill_chunk_tokens=0 if no_chunked_prefill else 128,
    )
    handle = serve.run(build_openai_app(cfg, name="perfllm", num_replicas=2))
    stream_handle = handle.options(stream=True)
    common = (
        "SYSTEM BOILERPLATE: you are a careful, terse assistant; follow "
        "the contract; cite sources; refuse what you must refuse; " * 2
    )[:260]
    # THREE families over two replicas whose pools hold TWO ~900-token
    # entries each: a stable {2 families, 1 family} partition exists and
    # digest routing maintains it (a correctly routed request refreshes
    # its own entry, evicting nothing); cache-blind routing bounces the
    # shared-header traffic and thrashes the 2-entry pools.
    systems = [
        common
        + f" FAMILY {i}: "
        + f"domain-{i} instructions and few-shot examples; " * 14
        for i in range(3)
    ]  # ~900 chars each: a full 1024-token prefill bucket when cold

    def one_request(prompt: str, max_tokens: int) -> dict:
        t0 = time.perf_counter()
        ttft, gaps, last, tokens = None, [], None, 0
        for _chunk in stream_handle.remote(
            {
                "path": "/perfllm/v1/completions",
                "body": {
                    "prompt": prompt,
                    "max_tokens": max_tokens,
                    "stream": True,
                },
            }
        ):
            now = time.perf_counter()
            if ttft is None:
                ttft = now - t0
            elif last is not None:
                gaps.append(now - last)
            last = now
            tokens += 1
        return {"ttft": ttft or 0.0, "gaps": gaps, "tokens": tokens}

    def run_mix(requests: list, workers: int) -> list:
        out = [None] * len(requests)
        with concurrent.futures.ThreadPoolExecutor(workers) as pool:
            futs = {
                pool.submit(one_request, p, mt): i
                for i, (p, mt) in enumerate(requests)
            }
            for f in concurrent.futures.as_completed(futs):
                out[futs[f]] = f.result()
        return out

    n_shared = 24 if quick else 60
    n_long = 4 if quick else 10
    n_short = 12 if quick else 30

    # Warm each prompt family twice (pass 1 pools by pow-2 wherever it
    # lands; pass 2, past the staleness window, routes on the advertised
    # digests and repairs any churn), so both arms measure steady-state
    # serving, not cold-start discovery.
    for _pass in range(2):
        for s in systems:
            one_request(s + " warmup", 2)
        time.sleep(GLOBAL_CONFIG.prefix_route_staleness_s + 1.5)

    shared_reqs = [
        (systems[i % len(systems)] + f" q{i}", 8) for i in range(n_shared)
    ]
    pre_hist = _hist_snapshot("raytpu_llm_ttft_seconds")
    t0 = time.perf_counter()
    res = run_mix(shared_reqs, workers=6)
    dt = time.perf_counter() - t0
    toks = sum(r["tokens"] for r in res)
    results["serve_llm_shared_prefix"] = round(toks / dt, 1)
    results["serve_llm_shared_prefix_p99_ttft_ms"] = _p99_ms(
        [r["ttft"] for r in res]
    )
    time.sleep(3.0)  # metric push interval: let replica snapshots land
    results["serve_llm_shared_ttft_gt250ms_pct"] = _hist_frac_above(
        pre_hist, _hist_snapshot("raytpu_llm_ttft_seconds"), "0.25"
    )
    print(
        f"serve_llm_shared_prefix: {results['serve_llm_shared_prefix']:,} "
        f"tok/s, p99 TTFT "
        f"{results['serve_llm_shared_prefix_p99_ttft_ms']} ms, engine "
        f"TTFT>250ms {results['serve_llm_shared_ttft_gt250ms_pct']:.1%}",
        flush=True,
    )

    # Mixed lengths: short decoders in flight while long COLD prompts
    # prefill (each long prompt is distinct — no cache help; unchunked,
    # its full-bucket prefill stalls every decoder sharing the replica).
    mixed = [
        (f"COLD DOCUMENT {i}: " + f"paragraph {i} " * 120, 8)
        for i in range(n_long)
    ] + [(f"quick question {i}?", 24) for i in range(n_short)]
    pre_hist = _hist_snapshot("raytpu_llm_itl_seconds")
    t0 = time.perf_counter()
    res = run_mix(mixed, workers=6)
    dt = time.perf_counter() - t0
    toks = sum(r["tokens"] for r in res)
    short_gaps = [g for r in res[n_long:] for g in r["gaps"]]
    results["serve_llm_mixed_len"] = round(toks / dt, 1)
    results["serve_llm_mixed_len_p99_ttft_ms"] = _p99_ms(
        [r["ttft"] for r in res]
    )
    results["serve_llm_mixed_len_p99_itl_ms"] = _p99_ms(short_gaps)
    time.sleep(3.0)
    # The stall criterion, on the engine's own clock: the share of
    # decode-loop inter-token gaps above 100 ms — an unchunked ~1024-token
    # prefill (~120 ms on this box) parks every in-flight decoder in the
    # >100 ms buckets; chunked prefill must empty them.
    results["serve_llm_mixed_itl_gt100ms_pct"] = _hist_frac_above(
        pre_hist, _hist_snapshot("raytpu_llm_itl_seconds"), "0.1"
    )
    print(
        f"serve_llm_mixed_len: {results['serve_llm_mixed_len']:,} tok/s, "
        f"p99 TTFT {results['serve_llm_mixed_len_p99_ttft_ms']} ms, "
        f"short-stream p99 ITL "
        f"{results['serve_llm_mixed_len_p99_itl_ms']} ms, engine "
        f"ITL>100ms {results['serve_llm_mixed_itl_gt100ms_pct']:.1%}",
        flush=True,
    )

    # Engine-side aggregates via the advertisement table: prefill_tokens
    # is the compute actually paid, prefix_tokens_reused the compute
    # routing+caching avoided — the mechanism behind the client metrics.
    time.sleep(2.0)  # let the last report-loop push land
    ctrl = ray_tpu.get_actor("serve::controller")
    st = ray_tpu.get(ctrl.get_router_state.remote("perfllm"), timeout=30)
    results["serve_llm_prefill_tokens"] = float(
        sum(
            ((i.get("state") or {}).get("prefill_tokens", 0))
            for i in st.values()
        )
    )
    results["serve_llm_prefix_tokens_reused"] = float(
        sum(
            ((i.get("state") or {}).get("prefix_tokens_reused", 0))
            for i in st.values()
        )
    )
    print(
        f"  engines: {results['serve_llm_prefill_tokens']:.0f} prefill "
        f"tokens paid, {results['serve_llm_prefix_tokens_reused']:.0f} "
        f"reused",
        flush=True,
    )

    # Routing outcome counters from THIS process (the router runs here).
    from ray_tpu.util.metrics import registry

    for name, key in (
        ("raytpu_serve_prefix_route_hits_total", "serve_llm_route_hits"),
        ("raytpu_serve_prefix_route_misses_total", "serve_llm_route_misses"),
    ):
        total = 0.0
        for n, _tags, v in registry().snapshot()["points"]:
            if n == name:
                total += v
        results[key] = total
    print(
        f"  routing: {results['serve_llm_route_hits']:.0f} hits / "
        f"{results['serve_llm_route_misses']:.0f} misses",
        flush=True,
    )
    serve.shutdown()

    # Controlled single-engine stall probe (no serve/driver noise, both
    # cores to one process): the worst inter-token gap three in-flight
    # decoders see while a cold ~950-token prompt is admitted — THE
    # number chunked prefill exists to bound. Unchunked, the gap is one
    # full-bucket prefill + a step; chunked, one chunk + a step.
    import statistics

    from ray_tpu.llm.config import SamplingParams
    from ray_tpu.llm.engine import LLMEngine

    eng = LLMEngine(
        LLMConfig(
            model_config=model,
            max_slots=4,
            max_seq=1024,
            prefill_buckets=(32, 128, 1024),
            num_kv_blocks=420,
            enable_prefix_caching=False,  # every long prompt stays cold
            prefill_chunk_tokens=0 if no_chunked_prefill else 128,
        )
    )
    eng.add_request("warm", "w" * 950, SamplingParams(max_tokens=2))
    while eng.has_unfinished():
        eng.step()  # compile both prefill paths + decode
    eng.pop_finished()
    for i in range(3):
        eng.add_request(f"d{i}", f"short {i}", SamplingParams(max_tokens=250))
    eng.step()
    eng.step()
    stalls = []
    for trial in range(3):
        eng.add_request(
            f"long{trial}", "y" * (930 + trial), SamplingParams(max_tokens=2)
        )
        gaps, t_last = [], time.perf_counter()
        for _ in range(64):
            eng.step()
            now = time.perf_counter()
            gaps.append(now - t_last)
            t_last = now
            if not any(
                r.request_id == f"long{trial}" and not r.finished
                for r in eng.requests.values()
            ):
                break
        eng.pop_finished()
        stalls.append(max(gaps))
    results["serve_llm_decode_stall_ms"] = round(
        statistics.median(stalls) * 1e3, 2
    )
    print(
        f"serve_llm_decode_stall_ms: "
        f"{results['serve_llm_decode_stall_ms']} ms (worst decoder gap "
        f"while a cold long prompt lands; median of 3)",
        flush=True,
    )

    # Disaggregated-serving stall probe (round 16): the same worst-gap
    # question, but the decode engine takes the long prompt as a KV
    # HANDOFF prefilled on a separate engine (the prefill tier) instead
    # of prefilling it locally — the decode clock pays only the pull +
    # scatter. --no-disagg is the OFF arm (local admission, = the
    # round-12 number).
    from ray_tpu.llm.engine import LLMEngine as _Eng

    probe_cfg = LLMConfig(
        model_config=model,
        max_slots=4,
        max_seq=1024,
        prefill_buckets=(32, 128, 1024),
        num_kv_blocks=420,
        enable_prefix_caching=False,
        prefill_chunk_tokens=0 if no_chunked_prefill else 128,
    )
    dec = _Eng(probe_cfg)
    pre = None if no_disagg else _Eng(probe_cfg)
    # Warm/compile every path each arm uses (prefill buckets, decode,
    # and — ON arm — the handoff gather/pull/scatter programs).
    dec.add_request("warm", "w" * 950, SamplingParams(max_tokens=2))
    while dec.has_unfinished():
        dec.step()
    dec.pop_finished()
    if pre is not None:
        pre.add_request(
            "warmp", "w" * 950, SamplingParams(max_tokens=2),
            prefill_only=True,
        )
        while pre.has_unfinished():
            pre.step()
        dec.add_handoff_request(
            "warmh", pre.pop_finished()[0].handoff_out,
            SamplingParams(max_tokens=2),
        )
        while dec.has_unfinished():
            dec.step()
        dec.pop_finished()
    for i in range(3):
        dec.add_request(
            f"dd{i}", f"short {i}", SamplingParams(max_tokens=250)
        )
    dec.step()
    dec.step()
    stalls = []
    for trial in range(3):
        rid = f"dlong{trial}"
        prompt = "y" * (930 + trial)
        if pre is None:
            dec.add_request(rid, prompt, SamplingParams(max_tokens=2))
        else:
            pre.add_request(
                rid, prompt, SamplingParams(max_tokens=2),
                prefill_only=True,
            )
            while pre.has_unfinished():
                pre.step()  # the prefill tier's clock, not the decoders'
            dec.add_handoff_request(
                rid, pre.pop_finished()[0].handoff_out,
                SamplingParams(max_tokens=2),
            )
        gaps, t_last = [], time.perf_counter()
        for _ in range(64):
            dec.step()
            now = time.perf_counter()
            gaps.append(now - t_last)
            t_last = now
            if not any(
                r.request_id == rid and not r.finished
                for r in dec.requests.values()
            ):
                break
        dec.pop_finished()
        stalls.append(max(gaps))
    results["serve_llm_disagg_stall_ms"] = round(
        statistics.median(stalls) * 1e3, 2
    )
    arm = "off (local prefill)" if no_disagg else "on (kv handoff)"
    print(
        f"serve_llm_disagg_stall_ms: "
        f"{results['serve_llm_disagg_stall_ms']} ms (worst decoder gap "
        f"while a cold long prompt joins the decode engine; disagg {arm})",
        flush=True,
    )

    # Speculative-decoding probe (round 16): decode-bound traffic on one
    # engine — greedy streams, no cache help. ON: a 1-layer draft
    # proposes k=4 per step, the target verifies in one batched forward.
    # Rows: decode tok/s, client-visible per-token p99 gap (burst tokens
    # land together: first pays the step, the rest ~0), accept rate.
    spec_kw = (
        {}
        if no_spec_decode
        else dict(
            spec_decode_tokens=4,
            draft_model_config=GPT2Config.tiny(
                n_layer=1, d_model=128, n_head=4, max_seq=1024
            ),
        )
    )
    eng_s = _Eng(
        LLMConfig(
            model_config=model,
            max_slots=4,
            max_seq=1024,
            prefill_buckets=(32, 128, 1024),
            num_kv_blocks=420,
            enable_prefix_caching=False,
            **spec_kw,
        )
    )
    eng_s.add_request("warm", "warm me", SamplingParams(max_tokens=8))
    while eng_s.has_unfinished():
        eng_s.step()
    eng_s.pop_finished()
    n_tok = 80 if quick else 200
    for i in range(3):
        eng_s.add_request(
            f"sp{i}", f"stream {i}", SamplingParams(max_tokens=n_tok)
        )
    tok0 = eng_s.stats["tokens_generated"]
    token_gaps: list = []
    t0 = time.perf_counter()
    t_last = t0
    while eng_s.has_unfinished():
        before = eng_s.stats["tokens_generated"]
        eng_s.step()
        now = time.perf_counter()
        produced = eng_s.stats["tokens_generated"] - before
        if produced:
            token_gaps.append(now - t_last)
            token_gaps.extend([0.0] * (produced - 1))
        t_last = now
    dt = time.perf_counter() - t0
    eng_s.pop_finished()
    toks = eng_s.stats["tokens_generated"] - tok0
    results["serve_llm_spec_decode_tok_s"] = round(toks / dt, 1)
    results["serve_llm_spec_itl_p99_ms"] = _p99_ms(token_gaps)
    drafted = eng_s.stats["spec_drafted"]
    results["serve_llm_spec_accept_rate"] = round(
        (eng_s.stats["spec_accepted"] / drafted) if drafted else 0.0, 4
    )
    arm = "off (vanilla)" if no_spec_decode else "on (k=4, 1-layer draft)"
    print(
        f"serve_llm_spec_decode: "
        f"{results['serve_llm_spec_decode_tok_s']:,} tok/s, per-token "
        f"p99 {results['serve_llm_spec_itl_p99_ms']} ms, accept rate "
        f"{results['serve_llm_spec_accept_rate']:.1%} [spec {arm}]",
        flush=True,
    )


def _serve_overload_rows(results: dict, no_admission: bool, quick: bool):
    """Overload-protection rows: a seeded flash crowd (tools/traffic_gen)
    fired open-loop at a slow 2-replica deployment whose admission config
    sheds on queue watermarks. The A/B (--no-admission) shows what the
    plane buys: with it, low-priority traffic absorbs the crowd as fast
    429-style rejections and admitted interactive p99 stays bounded;
    without it, every request queues and the whole tail collapses.

      serve_overload_shed_rate            rejected fraction of offered load
      serve_overload_admitted_p99_ttft_ms p99 latency of ADMITTED
                                          interactive requests (the SLO
                                          the plane protects)
      serve_overload_p99_ttft_ms          p99 over every completed request
      serve_overload_{admitted,shed,throttled} router admission counters
    """
    import sys as _sys

    from ray_tpu import serve
    from ray_tpu.core.errors import OverloadedError

    _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from traffic_gen import schedule, replay  # noqa: E402

    class SlowEcho:
        async def __call__(self, request):
            import asyncio as _a

            await _a.sleep(0.15)
            return {"ok": True}

    dep = serve.deployment(
        SlowEcho,
        name="overload",
        num_replicas=2,
        max_concurrent_queries=8,
        admission_config={
            "queue_high": 5.0,
            "queue_low": 2.0,
            "down_hold_s": 1.0,
            "retry_after_s": 0.2,
        },
    )
    handle = serve.run(dep.bind())
    sched = schedule(
        "flash_crowd",
        seed=7,
        duration_s=6.0 if quick else 12.0,
        base_rps=15.0,
        tenants=4,
        peak_factor=10.0,
    )

    def submit(a):
        t0 = time.perf_counter()
        try:
            handle.options(tenant=a.tenant, priority=a.priority).remote(
                {"body": {"i": a.index}}
            ).result(timeout=120)
            return ("ok", a.priority, time.perf_counter() - t0)
        except OverloadedError:
            return ("rejected", a.priority, time.perf_counter() - t0)

    outcomes = replay(sched, submit, max_workers=96)
    done = [o for o in outcomes if isinstance(o, tuple)]
    rejected = [o for o in done if o[0] == "rejected"]
    ok_interactive = [
        o for o in done if o[0] == "ok" and o[1] == "interactive"
    ]
    results["serve_overload_requests"] = len(sched)
    results["serve_overload_shed_rate"] = round(
        len(rejected) / max(1, len(done)), 4
    )
    results["serve_overload_admitted_p99_ttft_ms"] = _p99_ms(
        [o[2] for o in ok_interactive]
    )
    results["serve_overload_p99_ttft_ms"] = _p99_ms(
        [o[2] for o in done if o[0] == "ok"]
    )
    # Router-side admission counters (the routers run in THIS process).
    from ray_tpu.util.metrics import registry

    for decision in ("admitted", "shed", "throttled"):
        total = 0.0
        for n, tags, v in registry().snapshot()["points"]:
            if (
                n == "raytpu_serve_admission_total"
                and tags.get("decision") == decision
            ):
                total += v
        results[f"serve_overload_{decision}"] = total
    arm = "no-admission" if no_admission else "admission"
    print(
        f"serve_overload [{arm}]: {len(sched)} offered, shed rate "
        f"{results['serve_overload_shed_rate']:.1%}, admitted "
        f"interactive p99 "
        f"{results['serve_overload_admitted_p99_ttft_ms']} ms "
        f"(all-ok p99 {results['serve_overload_p99_ttft_ms']} ms)",
        flush=True,
    )
    serve.shutdown()


def _hist_sum_count(name: str) -> tuple:
    """(sum, count) of one histogram across this process's registry."""
    from ray_tpu.util.metrics import registry

    total, count = 0.0, 0.0
    for n, _tags, v in registry().snapshot()["points"]:
        if n == name and isinstance(v, dict):
            total += v["sum"]
            count += v["count"]
    return total, count


def _counter_total(name: str) -> float:
    from ray_tpu.util.metrics import registry

    total = 0.0
    for n, _tags, v in registry().snapshot()["points"]:
        if n == name:
            total += float(v)
    return total


def _train_rows(results: dict, no_async_dispatch: bool, quick: bool):
    """Host-free train-step rows (PERF.md round-13): a pure-jax
    single-process loop — tiny GPT-2, AOT-compiled donated step — feeding
    DEVICE-RESIDENT metrics through TrainContext.report() with batches
    staged by DevicePrefetchIterator. No cluster runtime: the A/B isolates
    exactly the host work on the step path.

      train_step_overlap          steps/s of the full loop (input + step +
                                  report)
      train_step_host_blocked_ms  host-blocked readback per step
                                  (raytpu_train_host_blocked_seconds
                                  delta / steps). In the OFF arm every
                                  report() waits for the step it just
                                  dispatched AND the loader then runs with
                                  the device idle; in the ON arm the ring
                                  eviction waits on a step dispatched
                                  ``depth`` steps ago while the loader's
                                  cost hides inside that wait
      train_prefetch_misses       staging underruns (consumer beat the
                                  input thread)

    ``--no-async-dispatch`` (= RAY_TPU_TRAIN_ASYNC_DISPATCH=0) is the OFF
    arm and restores the whole synchronous loop: sync readback inside
    every report() AND host-passthrough input (default-depth prefetch
    follows the same kill switch)."""
    import numpy as np

    from ray_tpu.core.config import GLOBAL_CONFIG

    if no_async_dispatch:
        GLOBAL_CONFIG.train_async_dispatch = False

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # The TPU plugin stomps the env var at import time; repin.
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from ray_tpu.models import gpt2
    from ray_tpu.train.context import TrainContext
    from ray_tpu.train.input import DevicePrefetchIterator
    from ray_tpu.train.spmd import (
        compile_train_step,
        default_optimizer,
        make_train_state,
        make_train_step,
    )

    cfg = gpt2.GPT2Config.tiny(n_layer=2, d_model=128, max_seq=128)
    steps = 40 if quick else 120
    B = 8
    opt = default_optimizer(total_steps=steps)
    state = make_train_state(
        lambda k: gpt2.init_params(k, cfg), opt, jax.random.key(0)
    )
    # donate_batch stays off: int32 token buffers have no dtype-matching
    # outputs to reuse, so donation would only emit XLA's unusable-donation
    # warning. donate_state off too: the CPU runtime blocks the dispatch
    # call until a donated input is defined (~the full step time), which
    # would hide the readback stall this A/B exists to measure (TPU
    # resolves aliasing asynchronously — bench.py keeps donation on).
    step = make_train_step(
        lambda p, b: gpt2.loss_fn(p, b, cfg), opt, donate_state=False
    )
    rng = np.random.default_rng(0)

    def host_batches():
        # Synthetic loader with REAL host cost per batch (~20-25 ms on
        # this box vs a ~55 ms step): an oversampled byte "corpus" folded
        # into vocab ids, standing in for tokenize+pack. This is the work
        # the overlap tier takes off the step path — the prefetch thread
        # absorbs it in the ON arm; the OFF arm (passthrough) pays it
        # inline between steps while the device sits idle.
        for _ in range(steps):
            raw = rng.integers(
                0, 256, size=(B * cfg.max_seq, 2048), dtype=np.int64
            )
            tokens = (
                (raw.cumsum(axis=1).sum(axis=1) % cfg.vocab_size)
                .astype(np.int32)
                .reshape(B, cfg.max_seq)
            )
            yield {"tokens": tokens, "targets": np.roll(tokens, -1, axis=1)}

    # AOT-compile against a staged example OUTSIDE the timed loop. lower()
    # only traces — donation happens when the executable runs — so the
    # example batch stays valid.
    example = jax.device_put(next(iter(host_batches())))
    compiled, _flops = compile_train_step(step, state, example)

    ctx = TrainContext(
        experiment_name="ray_perf",
        world_size=1,
        world_rank=0,
        local_rank=0,
        local_world_size=1,
        node_rank=0,
    )
    blocked0, _ = _hist_sum_count("raytpu_train_host_blocked_seconds")
    misses0 = _counter_total("raytpu_train_prefetch_misses_total")
    it = DevicePrefetchIterator(host_batches())
    input_wait = 0.0  # consumer-thread time spent obtaining the next batch
    t0 = time.perf_counter()
    while True:
        t_in = time.perf_counter()
        try:
            batch = next(it)
        except StopIteration:
            break
        input_wait += time.perf_counter() - t_in
        state, metrics = compiled(state, batch)
        ctx.report(metrics)
    ctx.flush()
    jax.block_until_ready(state["step"])
    dt = time.perf_counter() - t0
    blocked1, _ = _hist_sum_count("raytpu_train_host_blocked_seconds")
    reports = ctx.drain_reports()
    assert len(reports) == steps, (len(reports), steps)

    results["train_step_overlap"] = round(steps / dt, 2)
    # Host-blocked = everything the consumer thread did per step that was
    # NOT dispatching: metric readback stalls (the histogram) + obtaining
    # the next batch (inline loader+h2d in the OFF arm; a queue pop —
    # usually instant — in the ON arm). The tier's whole point is driving
    # this toward pure device-wait while steps/s rises.
    results["train_step_host_blocked_ms"] = round(
        ((blocked1 - blocked0) + input_wait) * 1e3 / steps, 4
    )
    results["train_prefetch_misses"] = (
        _counter_total("raytpu_train_prefetch_misses_total") - misses0
    )
    arm = "off (sync readback)" if no_async_dispatch else (
        f"on (depth {GLOBAL_CONFIG.train_async_dispatch_depth})"
    )
    print(
        f"train_step_overlap: {results['train_step_overlap']:,.1f} steps/s, "
        f"host-blocked {results['train_step_host_blocked_ms']:.3f} ms/step, "
        f"{results['train_prefetch_misses']:.0f} prefetch misses "
        f"[async dispatch {arm}]",
        flush=True,
    )


def _elastic_train_fn(config):
    """Worker loop for the elastic-recovery probe: deterministic
    replicated numpy state retained via ``elastic_state=`` every step,
    plus a checkpoint round every ``ckpt_every`` steps so the
    ``--no-elastic`` arm has something to restore from. Module-level so
    worker processes can unpickle it."""
    import os as _os
    import tempfile as _tmp
    import time as _t

    import numpy as _np

    import ray_tpu.train as train

    ctx = train.get_context()
    el = train.get_elastic_state()
    if el is not None:
        # Live re-formation: resume from the peer-resharded state — no
        # checkpoint-storage read on this path.
        state = _np.asarray(el["state"])
        start = int(el["index"]) + 1
    else:
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with ckpt.as_directory() as d:
                state = _np.load(_os.path.join(d, "state.npy"))
            start = int(state[1]) + 1
        else:
            state = _np.zeros(2)
            start = 0
    for step in range(start, int(config["steps"])):
        state = state + _np.asarray([1.0, 0.0])
        state[1] = float(step)
        if (
            step % int(config.get("ckpt_every", 5)) == 0
            and ctx.get_world_rank() == 0
        ):
            with _tmp.TemporaryDirectory() as d:
                _np.save(_os.path.join(d, "state.npy"), state)
                train.report(
                    {"step": step},
                    checkpoint=train.Checkpoint(d),
                    elastic_state=state,
                )
        else:
            train.report({"step": step}, elastic_state=state)
        _t.sleep(float(config.get("step_s", 0.05)))


def _train_elastic_rows(results: dict, no_elastic: bool, quick: bool):
    """Elastic-recovery probe (round-21 robustness A/B): a 2-node
    in-process cluster runs a 2-worker gang whose train fn retains
    ``elastic_state=`` every step; mid-run the second node gets a
    graceful drain notice (the preemption lifecycle). The ON arm pauses
    the survivor at its next step boundary, reshards state peer-to-peer,
    and resumes at world size 1 in the SAME generation; the OFF arm
    (``--no-elastic`` = RAY_TPU_ELASTIC_TRAIN=0) tears the gang down and
    rebuilds from the latest checkpoint. Both arms stamp the SAME
    interval — drain notice observed -> first post-recovery report — so
    the row is directly comparable:

      train_elastic_recovery_ms   drain seen -> first report after
                                  recovery
      train_elastic_reshapes      raytpu_train_reshapes_total delta
                                  (1 shrink in the ON arm, 0 in OFF)
      train_elastic_end_world     raytpu_train_world_size after the run
                                  (1 = re-formed smaller; 2 = rebuilt at
                                  full size from the checkpoint)
    """
    import tempfile
    import threading

    import ray_tpu
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.train import elastic as train_elastic
    from ray_tpu.train import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train.controller import TrainController

    GLOBAL_CONFIG.elastic_train = not no_elastic
    GLOBAL_CONFIG.elastic_grow_check_s = 0.0  # probe measures the shrink
    GLOBAL_CONFIG.drain_grace_s = 30.0

    runtime = ray_tpu.init(num_cpus=2)
    node2 = runtime.add_node({"CPU": 1.0})
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        v = runtime.head.cluster_view.get(node2.node_id)
        if v is not None and v.alive:
            break
        time.sleep(0.1)
    else:
        raise TimeoutError("second node never joined the head's view")

    steps = 60 if quick else 120
    storage = tempfile.mkdtemp(prefix="raytpu_elastic_perf_")
    controller = TrainController(
        _elastic_train_fn,
        {"steps": steps, "ckpt_every": 5, "step_s": 0.05},
        ScalingConfig(
            num_workers=2,
            resources_per_worker={"CPU": 1},
            # SPREAD (soft): one worker per node while both nodes live,
            # and the --no-elastic rebuild can still pack both workers
            # onto the survivor after the drained node dies.
            placement_strategy="SPREAD",
        ),
        RunConfig(
            name="elastic_probe",
            storage_path=storage,
            # Zero failure budget: BOTH recovery paths classify the drain
            # as "preempted" and must not burn max_failures.
            failure_config=FailureConfig(max_failures=0),
        ),
        BackendConfig(),
    )
    reshapes0 = _counter_total("raytpu_train_reshapes_total")
    box: dict = {}

    def _fit():
        box["result"] = controller.run()

    th = threading.Thread(target=_fit, daemon=True)
    th.start()
    # Drain only once the gang is actually running with a rank on node2 —
    # a notice during SCHEDULING would just steer placement off the node
    # and measure nothing.
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        grp = controller._active_group
        if (
            controller.state == "RUNNING"
            and grp is not None
            and any(
                w.metadata["node_id"] == node2.node_id for w in grp.workers
            )
        ):
            break
        time.sleep(0.1)
    else:
        raise TimeoutError("gang never started with a rank on node2")
    time.sleep(0.5)  # a few steps of progress (and a checkpoint round)
    ray_tpu.drain_node(node2.node_id, grace_s=30.0, reason="preempted")
    th.join(timeout=180)
    result = box.get("result")
    if result is None or result.error is not None:
        raise RuntimeError(
            f"elastic probe run did not finish cleanly: "
            f"{getattr(result, 'error', 'run() still going')}"
        )

    rec_ms = train_elastic.last_recovery_ms()
    results["train_elastic_recovery_ms"] = (
        round(rec_ms, 1) if rec_ms is not None else None
    )
    results["train_elastic_reshapes"] = (
        _counter_total("raytpu_train_reshapes_total") - reshapes0
    )
    results["train_elastic_end_world"] = _counter_total(
        "raytpu_train_world_size"
    )
    arm = (
        "off (checkpoint rebuild)"
        if no_elastic
        else "on (live re-formation)"
    )
    print(
        f"train_elastic_recovery_ms: {results['train_elastic_recovery_ms']}"
        f" ms, {results['train_elastic_reshapes']:.0f} reshapes, end world "
        f"{results['train_elastic_end_world']:.0f} [elastic {arm}]",
        flush=True,
    )
    ray_tpu.shutdown()


def _podracer_env_maker():
    """CartPole with a ~0.25 ms per-env-step cost emulating a non-trivial
    simulator (a raw CartPole step is ~1 µs — three orders of magnitude
    under any production env, which would make ANY acting-plane design
    look control-plane-bound). Module-level so worker processes can
    unpickle it."""
    import time as _t

    import gymnasium as gym

    class _SlowStep(gym.Wrapper):
        def step(self, action):
            _t.sleep(0.00025)
            return self.env.step(action)

    return _SlowStep(gym.make("CartPole-v1"))


def _rl_rows(results: dict, no_podracer: bool, quick: bool):
    """Podracer RL rows: one fixed-budget DQN run on the emulated-cost
    CartPole (see _podracer_env_maker), decoupled planes ON (HEAD
    defaults) vs the --no-podracer kill switch (the single-loop
    sample→update iteration, byte-identical to DQN). Rows:

      rl_env_steps_per_s        acting-plane throughput — the headline
      rl_learner_updates_per_s  grad steps/s landed alongside the acting
      rl_weight_lag_p99         p99 published-vs-applied version lag
                                (bounded by podracer_staleness_steps;
                                identically 0 on the lockstep arm)
      rl_inference_batch_mean   coalesced rows per inference forward
                                (decoupled arm only)
    """
    from ray_tpu.rllib import PodracerConfig

    target = 4000 if quick else 12000
    arm = "single-loop" if no_podracer else "podracer"
    config = PodracerConfig(
        num_env_runners=2,
        num_envs_per_env_runner=16,
        rollout_fragment_length=16,
        lr=1e-3,
        hidden=(128, 128),
        seed=0,
        epsilon_anneal_steps=4 * target,
        learning_starts=512,
        train_batch_size=256,
        num_train_batches_per_iteration=16,
        target_network_update_freq=200,
        podracer_staleness_steps=2,
        trajectory_queue_depth=8,
        inference_batch_window_s=0.001,
        inference_max_batch=64,
    ).environment(_podracer_env_maker)
    algo = config.build()
    # Warm the jitted paths out of the measured window (both arms pay
    # their compiles here). The warmup must run PAST learning_starts so
    # the learner's update/scatter programs compile now, not inside the
    # measured window.
    algo.run(1_536, time_budget_s=180)
    t0 = time.perf_counter()
    out = algo.run(target, time_budget_s=300 if quick else 600)
    dt = time.perf_counter() - t0
    results["rl_env_steps_per_s"] = round(out["env_steps"] / dt, 1)
    results["rl_learner_updates_per_s"] = round(
        out["grad_updates"] / dt, 2
    )
    results["rl_weight_lag_p99"] = round(out["weight_lag_p99"], 2)
    infer = out.get("inference") or {}
    if infer.get("batches"):
        results["rl_inference_batch_mean"] = round(
            infer["rows"] / infer["batches"], 2
        )
    results["rl_restarts"] = out.get("restarts", 0)
    results["rl_queue_drops"] = out.get("queue_drops", 0)
    print(
        f"rl [{arm}]: {results['rl_env_steps_per_s']:,.0f} env_steps/s, "
        f"{results['rl_learner_updates_per_s']:,.1f} updates/s, "
        f"weight-lag p99 {results['rl_weight_lag_p99']}",
        flush=True,
    )
    algo.stop()


def _data_rows(results: dict, quick: bool) -> None:
    """Governed out-of-core data-pipeline rows (round-18 memory-governed
    streaming data plane): the object store is capped WELL below the
    dataset size, a map pipeline streams ~4x the cap through
    iter_batches, and the rows report throughput + how the store
    behaved. The caller shrank GLOBAL_CONFIG.object_store_bytes BEFORE
    init (capacity is fixed at store creation) and flipped
    data_governor for the --no-data-governor arm."""
    import threading

    import ray_tpu.data as rd
    from ray_tpu.core.config import GLOBAL_CONFIG

    cap = GLOBAL_CONFIG.object_store_bytes
    n_blocks = 16 if quick else 32
    rows_per_block = 128
    # ~8 MB/block: 1024 float64 payload lanes per row.
    lanes = 8 * 1024 * 1024 // (rows_per_block * 8)

    peak = [0]
    spills = [0]
    stop = [False]

    def poll():
        while not stop[0]:
            used = sp = 0
            for n in ray_tpu.nodes():
                st = n.get("StoreStats") or {}
                used += int(st.get("used_bytes", 0))
                sp += int(st.get("spills", 0))
            peak[0] = max(peak[0], used)
            spills[0] = sp
            time.sleep(0.025)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    payload = lambda b: {  # noqa: E731 — shipped by value to workers
        "id": b["id"],
        "x": np.ones((len(b["id"]), lanes), np.float64),
    }
    ds = rd.range(n_blocks * rows_per_block, parallelism=n_blocks)
    ds = ds.map_batches(payload)
    t0 = time.perf_counter()
    rows = 0
    for batch in ds.iter_batches(batch_size=rows_per_block):
        rows += len(batch["id"])
    dt = time.perf_counter() - t0
    stop[0] = True
    poller.join()
    results["data_pipeline_rows_per_s"] = round(rows / dt, 1)
    results["data_peak_store_frac"] = round(peak[0] / cap, 3)
    results["data_store_spills"] = spills[0]
    gov = ds.governor_stats()
    results["data_throttle_events"] = (
        0 if gov is None else gov["throttle_events"]
    )
    print(
        f"data_pipeline [{'governed' if gov is not None else 'kill-switch'}]"
        f": {results['data_pipeline_rows_per_s']:,.0f} rows/s, peak store "
        f"{results['data_peak_store_frac']:.0%} of cap, "
        f"{results['data_store_spills']} spills, "
        f"{results['data_throttle_events']} throttles",
        flush=True,
    )


def _pctl_ms(sorted_ms: list, q: float) -> float:
    if not sorted_ms:
        return 0.0
    return round(sorted_ms[min(len(sorted_ms) - 1, int(q * len(sorted_ms)))], 4)


def _fleet_rows(results: dict, quick: bool) -> None:
    """Fleet-scale control-plane rows (round-19): the in-process fleet
    emulator (core/fleet_emu.py) drives the REAL GCS wire handlers at
    100/500/1,000 emulated nodes from one seeded lease schedule and
    reports exact per-pick placement latency (read off
    ``gcs.place_latency_ms`` — no RPC overhead in the number), heartbeat
    RPC cost, and view-delta wire size per changed node. No cluster
    runtime: the GCS + one shared host endpoint is the whole process
    tree. The ``--no-sched-index`` arm re-runs the SAME tape through the
    original full-scan ``pick_node`` (tools/ab_fleet.py and bench.py's
    fleet_scale record ride this pair)."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.fleet_emu import FleetEmulator, schedule_events

    ops = 150 if quick else GLOBAL_CONFIG.fleet_emu_lease_ops
    seed = 19
    arm = "index" if GLOBAL_CONFIG.sched_index else "scan"
    for n in (100, 500, 1000):
        tape = schedule_events(seed, "steady", n, ops)
        with FleetEmulator(n, seed=seed) as emu:
            emu.register_all()
            # Registration pre-populates the latency deque with nothing
            # (no picks yet); every sample below is a real placement.
            emu.run_schedule(tape)
            lat = sorted(emu.place_latencies_ms())
            results[f"fleet_place_p50_ms_{n}"] = _pctl_ms(lat, 0.50)
            results[f"fleet_place_p99_ms_{n}"] = _pctl_ms(lat, 0.99)
            results[f"fleet_decision_digest_{n}"] = emu.decision_digest()
            if n == 1000:
                results["fleet_hb_ingest_us"] = round(
                    emu.heartbeat_burst_us(200 if quick else 500), 1
                )
                cursor = emu.delta_probe(-1)["version"]
                live = [e for e in emu.emu_nodes.values() if e.alive]
                for e in live[:50]:
                    e.available = dict(e.available)
                    e.available["CPU"] = max(
                        0.0, e.available.get("CPU", 0.0) - 0.5
                    )
                    emu.heartbeat(e)
                probe = emu.delta_probe(cursor)
                results["fleet_delta_bytes_per_node"] = round(
                    probe["bytes"] / max(1, probe["changed"]), 1
                )
                results["fleet_delta_nodes"] = probe["changed"]
            print(
                f"fleet_scale [{arm}] {n} nodes: place p50 "
                f"{results[f'fleet_place_p50_ms_{n}']} ms, p99 "
                f"{results[f'fleet_place_p99_ms_{n}']} ms "
                f"({len(lat)} picks)",
                flush=True,
            )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--no-coalesce",
        action="store_true",
        help="kill switch: one-write-per-frame transport, unbatched "
        "lease/submission paths (the A/B baseline for PERF.md round-6)",
    )
    ap.add_argument(
        "--no-metrics",
        action="store_true",
        help="kill switch: disable all runtime telemetry (equivalent to "
        "RAY_TPU_METRICS_ENABLED=0) — the A/B baseline proving the "
        "instrumentation tax stays within the 5%% budget",
    )
    ap.add_argument(
        "--no-scatter-gather",
        action="store_true",
        help="kill switch: in-band frame pickling + join-based flush "
        "(the A/B baseline for the PERF.md round-8 data plane)",
    )
    ap.add_argument(
        "--data-plane-only",
        action="store_true",
        help="run only the large-object rows (bench.py rides this for "
        "the BENCH_r* data-plane record)",
    )
    ap.add_argument(
        "--no-hierarchical",
        action="store_true",
        help="kill switch: flat one-ring collectives (equivalent to "
        "RAY_TPU_HIERARCHICAL_COLLECTIVES=0) — the A/B baseline for the "
        "PERF.md round-11 hierarchical-collective tier",
    )
    ap.add_argument(
        "--no-quantized",
        action="store_true",
        help="keep the hierarchical structure but ship the DCN leg at "
        "full precision (no block-int8 codec) — isolates the "
        "quantization arm of the round-11 A/B",
    )
    ap.add_argument(
        "--serve-llm-only",
        action="store_true",
        help="run only the LLM-serving rows (2 tiny-model replicas on "
        "CPU jax, streaming clients): serve_llm_shared_prefix / "
        "serve_llm_mixed_len tok/s + p99 TTFT/ITL — the round-12 "
        "cache-aware-serving A/B rides this via tools/ab_prefix_routing.py",
    )
    ap.add_argument(
        "--no-prefix-routing",
        action="store_true",
        help="kill switch: cache-blind router (equivalent to "
        "RAY_TPU_PREFIX_ROUTING=0) — the A/B baseline for prefix-affinity "
        "routing (PERF.md round-12)",
    )
    ap.add_argument(
        "--no-chunked-prefill",
        action="store_true",
        help="serve-llm rows only: engines admit with whole-suffix "
        "prefill (prefill_chunk_tokens=0) — the A/B baseline for chunked "
        "prefill (PERF.md round-12)",
    )
    ap.add_argument(
        "--no-disagg",
        action="store_true",
        help="kill switch: unified serving — the disagg stall probe's "
        "long prompts prefill LOCALLY on the decode engine (equivalent "
        "to RAY_TPU_DISAGG=0; the A/B baseline for the round-16 "
        "prefill/decode split)",
    )
    ap.add_argument(
        "--no-spec-decode",
        action="store_true",
        help="kill switch: vanilla one-token decode on the spec probe "
        "(equivalent to RAY_TPU_SPEC_DECODE=0; the A/B baseline for "
        "round-16 speculative decoding)",
    )
    ap.add_argument(
        "--serve-overload",
        action="store_true",
        help="run only the overload-protection rows (seeded flash crowd "
        "from tools/traffic_gen.py against a slow 2-replica deployment): "
        "serve_overload_shed_rate + admitted-interactive p99 — the "
        "admission A/B rides this via tools/ab_admission.py and "
        "bench.py's serve_overload record",
    )
    ap.add_argument(
        "--no-admission",
        action="store_true",
        help="kill switch: no admission control, priority shedding, or "
        "bounded replica queues (equivalent to RAY_TPU_ADMISSION=0) — "
        "the A/B baseline for the overload-protection tier",
    )
    ap.add_argument(
        "--train-only",
        action="store_true",
        help="run only the host-free train-step rows (pure-jax CPU loop, "
        "no cluster): train_step_overlap steps/s + host-blocked ms/step — "
        "the round-13 async-dispatch A/B rides this via "
        "tools/ab_train_overlap.py and bench.py's train_overlap record",
    )
    ap.add_argument(
        "--no-async-dispatch",
        action="store_true",
        help="kill switch: synchronous train loop — device->host metric "
        "readback inside every report() (equivalent to "
        "RAY_TPU_TRAIN_ASYNC_DISPATCH=0) — the A/B baseline for the "
        "round-13 host-free train steps",
    )
    ap.add_argument(
        "--elastic-probe",
        action="store_true",
        help="with --train-only: run the elastic-recovery row instead "
        "(2-node in-process cluster, 2-worker gang, graceful drain "
        "notice mid-run): train_elastic_recovery_ms = drain seen -> "
        "first report after recovery — the round-21 robustness A/B "
        "rides this via bench.py's train_elastic record",
    )
    ap.add_argument(
        "--no-elastic",
        action="store_true",
        help="kill switch: membership changes tear the gang down and "
        "rebuild from the latest checkpoint (equivalent to "
        "RAY_TPU_ELASTIC_TRAIN=0) — the A/B baseline for the round-21 "
        "elastic live re-formation",
    )
    ap.add_argument(
        "--rl-only",
        action="store_true",
        help="run only the podracer RL rows (decoupled DQN on an "
        "emulated-cost CartPole): rl_env_steps_per_s + learner updates/s "
        "+ weight-lag p99 — the round-17 A/B rides this via "
        "tools/ab_podracer.py and bench.py's podracer record",
    )
    ap.add_argument(
        "--no-podracer",
        action="store_true",
        help="kill switch: single-loop sample→update DQN iteration "
        "(equivalent to RAY_TPU_PODRACER=0; the A/B baseline for the "
        "round-17 decoupled actor/inference/learner planes)",
    )
    ap.add_argument(
        "--data-only",
        action="store_true",
        help="run only the governed out-of-core data-pipeline rows "
        "(object store capped ~4x below the dataset): rows/s + peak "
        "store occupancy + spills — the round-18 memory-governor A/B "
        "rides this via tools/ab_data_governor.py and bench.py's "
        "data_governor record",
    )
    ap.add_argument(
        "--no-data-governor",
        action="store_true",
        help="kill switch: ungoverned streaming executor (equivalent to "
        "RAY_TPU_DATA_GOVERNOR=0) — the A/B baseline for the round-18 "
        "memory-governed data plane; on the --data-only workload this "
        "arm spills where the governed arm stays under the watermark",
    )
    ap.add_argument(
        "--fleet-only",
        action="store_true",
        help="run only the fleet-scale control-plane rows (in-process "
        "fleet emulator at 100/500/1,000 emulated nodes driving the real "
        "GCS handlers, no cluster runtime): placement p50/p99 per scale, "
        "heartbeat RPC µs/msg, view-delta bytes/node — the round-19 "
        "scheduler-index A/B rides this via tools/ab_fleet.py and "
        "bench.py's fleet_scale record",
    )
    ap.add_argument(
        "--no-sched-index",
        action="store_true",
        help="kill switch: every placement decision takes the original "
        "full-scan pick_node path (equivalent to RAY_TPU_SCHED_INDEX=0) "
        "— the A/B baseline for the round-19 feasibility-indexed "
        "scheduler",
    )
    ap.add_argument(
        "--no-flightrec",
        action="store_true",
        help="kill switch: no flight-recorder phase events anywhere "
        "(equivalent to RAY_TPU_FLIGHTREC=0) — the A/B baseline for the "
        "observability plane; the ON arm must stay within ~3%% on the "
        "serve p99 probe (bench.py's obs_overhead record rides "
        "--serve-overload via tools/ab_tracing.py)",
    )
    ap.add_argument(
        "--faults",
        metavar="SEED:SPEC",
        help="enable the fault-injection plane for the whole run "
        "(RAY_TPU_FAULTS syntax; includes the node.preempt rule — a "
        "seeded graceful-drain notice) — the chaos-overhead arm of the "
        "robustness A/B; the default arm (injector off) must stay "
        "within noise of the pre-robustness numbers",
    )
    args = ap.parse_args()
    if args.faults:
        from ray_tpu.core import faults as _faults

        # Spawned worker processes re-import faults and read the env var;
        # without this, worker-side fault sites silently never fire.
        os.environ["RAY_TPU_FAULTS"] = args.faults
        _faults.install(_faults.parse_env(args.faults))
    batch = 20 if args.quick else 100
    min_s = 0.5 if args.quick else 2.0

    if args.train_only:
        # Pure-jax in-process rows: no cluster runtime, both cores to the
        # jitted step. CPU jax even where a TPU plugin is installed.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        results = {}
        if args.elastic_probe:
            _train_elastic_rows(
                results, no_elastic=args.no_elastic, quick=args.quick
            )
        else:
            _train_rows(
                results,
                no_async_dispatch=args.no_async_dispatch,
                quick=args.quick,
            )
        print(json.dumps(results), flush=True)
        return 0

    if (
        args.no_coalesce
        or args.no_metrics
        or args.no_scatter_gather
        or args.no_hierarchical
        or args.no_quantized
        or args.no_prefix_routing
        or args.no_admission
        or args.no_disagg
        or args.no_spec_decode
        or args.no_podracer
        or args.no_data_governor
        or args.no_sched_index
        or args.no_flightrec
    ):
        from ray_tpu.core.config import GLOBAL_CONFIG

        # Before init: the head ships this config to every node/worker.
        if args.no_coalesce:
            GLOBAL_CONFIG.rpc_coalesce_enabled = False
        if args.no_metrics:
            GLOBAL_CONFIG.metrics_enabled = False
        if args.no_scatter_gather:
            GLOBAL_CONFIG.rpc_scatter_gather_enabled = False
        if args.no_hierarchical:
            GLOBAL_CONFIG.hierarchical_collectives = False
        if args.no_quantized:
            GLOBAL_CONFIG.collective_quantize_dcn = False
        if args.no_prefix_routing:
            GLOBAL_CONFIG.prefix_routing = False
        if args.no_admission:
            GLOBAL_CONFIG.admission = False
        if args.no_disagg:
            GLOBAL_CONFIG.disagg = False
        if args.no_spec_decode:
            GLOBAL_CONFIG.spec_decode = False
        if args.no_podracer:
            GLOBAL_CONFIG.podracer = False
        if args.no_data_governor:
            GLOBAL_CONFIG.data_governor = False
        if args.no_sched_index:
            GLOBAL_CONFIG.sched_index = False
        if args.no_flightrec:
            GLOBAL_CONFIG.flightrec = False

    if args.fleet_only:
        # In-process emulator rows: no cluster runtime at all (the GCS +
        # one shared host endpoint IS the process tree).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        results = {}
        _fleet_rows(results, quick=args.quick)
        print(json.dumps(results), flush=True)
        return 0

    if args.data_only:
        # The store must be capped BEFORE init (capacity is fixed at
        # store creation): 4x below the dataset the rows stream through.
        from ray_tpu.core.config import GLOBAL_CONFIG as _DCFG

        _DCFG.object_store_bytes = 32 * 1024 * 1024
        ray_tpu.init(num_cpus=4)
        results = {}
        _data_rows(results, quick=args.quick)
        print(json.dumps(results), flush=True)
        ray_tpu.shutdown()
        return 0

    if args.rl_only:
        # Runner/learner jax stays on CPU even where a TPU plugin is
        # installed: workers inherit the driver env.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.serve_llm_only:
        # Replica actors must run CPU jax even where a TPU plugin is
        # installed: workers inherit the driver env.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    ray_tpu.init(num_cpus=16)
    results = {}

    if args.serve_llm_only:
        _serve_llm_rows(
            results,
            no_chunked_prefill=args.no_chunked_prefill,
            quick=args.quick,
            no_disagg=args.no_disagg,
            no_spec_decode=args.no_spec_decode,
        )
        print(json.dumps(results), flush=True)
        ray_tpu.shutdown()
        return 0

    if args.serve_overload:
        _serve_overload_rows(
            results, no_admission=args.no_admission, quick=args.quick
        )
        print(json.dumps(results), flush=True)
        ray_tpu.shutdown()
        return 0

    if args.rl_only:
        _rl_rows(results, no_podracer=args.no_podracer, quick=args.quick)
        print(json.dumps(results), flush=True)
        ray_tpu.shutdown()
        return 0

    def record(name, fn, multiplier=1):
        n, rate = timeit(name, fn, multiplier, min_s=min_s)
        results[n] = rate

    # -- large objects (round-8 data plane) ----------------------------------
    # put_large: driver put through the shm single-copy path. get_large:
    # a BORROWER (actor-side) get of a driver-owned inline object — the
    # leg where the value actually rides RPC frames, so the scatter-gather
    # A/B shows here. actor_array_args: multi-MB array args on pipelined
    # actor calls (args always ride the push frame, at any size).
    from ray_tpu.core.config import GLOBAL_CONFIG as _CFG

    large = np.zeros(8 * 1024 * 1024, dtype=np.uint8)  # 8 MB
    mb = large.nbytes / 1e6

    def put_large():
        ref = ray_tpu.put(large)
        del ref

    n, rate = timeit("put_large", put_large, 1, min_s=min_s, max_iters=30)
    results[n] = round(rate * mb, 2)
    print(f"  -> {results[n]:.1f} MB/s", flush=True)

    @ray_tpu.remote
    class _DataSink:
        def checksum(self, x):
            return int(x[0]) + int(x[-1])

        def fetch(self, ref):
            return int(ray_tpu.get(ref[0])[0])

    dsink = _DataSink.remote()
    ray_tpu.get(dsink.checksum.remote(np.zeros(8, dtype=np.uint8)))

    # Owner-side inline storage for the borrower-get row: bump the inline
    # cap (driver-side decision only) so the 8 MB value is served from the
    # owner's memory store over RPC instead of the shm file plane.
    old_inline = _CFG.max_inline_object_bytes
    _CFG.max_inline_object_bytes = large.nbytes + 1
    try:
        inline_ref = ray_tpu.put(large)
    finally:
        _CFG.max_inline_object_bytes = old_inline

    def get_large():
        ray_tpu.get(dsink.fetch.remote([inline_ref]))

    n, rate = timeit("get_large", get_large, 1, min_s=min_s, max_iters=30)
    results[n] = round(rate * mb, 2)
    print(f"  -> {results[n]:.1f} MB/s", flush=True)

    def actor_array_args():
        ray_tpu.get(
            [dsink.checksum.remote(large) for _ in range(4)]
        )

    n, rate = timeit(
        "actor_array_args", actor_array_args, 4, min_s=min_s, max_iters=20
    )
    results[n] = round(rate * mb, 2)
    print(f"  -> {results[n]:.1f} MB/s", flush=True)

    if args.data_plane_only:
        print(json.dumps(results), flush=True)
        ray_tpu.shutdown()
        return 0

    # -- objects -------------------------------------------------------------
    small = b"x" * 1024

    def put_small():
        for _ in range(batch):
            ray_tpu.put(small)

    record("single_client_put_calls_1kb", put_small, batch)

    ref_small = ray_tpu.put(small)

    def get_small():
        for _ in range(batch):
            ray_tpu.get(ref_small)

    record("single_client_get_calls_1kb", get_small, batch)

    big = np.zeros(64 * 1024 * 1024, dtype=np.uint8)  # 64 MB through shm

    def put_big():
        ref = ray_tpu.put(big)
        del ref

    n, rate = timeit(
        "single_client_put_gigabytes", put_big, 1, min_s=min_s, max_iters=20
    )
    results[n] = rate * big.nbytes / 1e9
    print(f"  -> {results[n]:.2f} GB/s", flush=True)

    # -- tasks ---------------------------------------------------------------
    def tasks_sync():
        for _ in range(batch):
            ray_tpu.get(tiny.remote())

    record("single_client_tasks_sync", tasks_sync, batch)

    def tasks_async():
        ray_tpu.get([tiny.remote() for _ in range(batch * 5)])

    record("single_client_tasks_async", tasks_async, batch * 5)

    # -- actors --------------------------------------------------------------
    sink = Sink.remote()
    ray_tpu.get(sink.ping.remote())

    def actor_sync():
        for _ in range(batch):
            ray_tpu.get(sink.ping.remote())

    record("1_1_actor_calls_sync", actor_sync, batch)

    def actor_async():
        ray_tpu.get([sink.ping.remote() for _ in range(batch * 5)])

    record("1_1_actor_calls_async", actor_async, batch * 5)

    def actor_with_arg():
        ray_tpu.get([sink.with_arg.remote(small) for _ in range(batch * 2)])

    record("1_1_actor_calls_with_arg_async", actor_with_arg, batch * 2)

    asink = Sink.options(max_concurrency=8).remote()
    ray_tpu.get(asink.aping.remote())

    def async_actor_async():
        ray_tpu.get([asink.aping.remote() for _ in range(batch * 5)])

    record("1_1_async_actor_calls_async", async_actor_async, batch * 5)

    # n:n — 4 actors, submissions interleaved from one driver (our driver is
    # one process; the reference uses n driver processes).
    sinks = [Sink.remote() for _ in range(4)]
    ray_tpu.get([s.ping.remote() for s in sinks])

    def n_n_async():
        refs = []
        for _ in range(batch * 2):
            for s in sinks:
                refs.append(s.ping.remote())
        ray_tpu.get(refs)

    record("n_n_actor_calls_async", n_n_async, batch * 2 * len(sinks))

    # -- collectives (round-11 hierarchical + quantized DCN) -----------------
    # Two allreduce rows over real member-actor gangs on the coordinator
    # data plane: a 2-slice group (slice identities passed explicitly, so
    # auto strategy picks hierarchical unless --no-hierarchical) and a
    # 1-slice group (always flat — the parity row: hierarchical selection
    # must not touch it). Bytes ride MB/s like the data-plane rows; the
    # dcn byte counters from rank 0's process give the quantization ratio.

    @ray_tpu.remote(num_cpus=0)
    class _CollMember:
        def __init__(self, world, rank, group, slice_name):
            from ray_tpu.util import collective as col

            self._col = col
            self._group = group
            self._comm = col.init_collective_group(
                world, rank, backend="cpu", group_name=group,
                timeout_s=120.0, slice_name=slice_name,
            )

        def strategy(self):
            return self._comm.backend

        def allreduce(self, n):
            t = np.ones(n, np.float32)
            out = self._col.allreduce(t, group_name=self._group)
            return float(np.asarray(out)[0])

        def dcn_bytes(self):
            from ray_tpu.util.metrics import registry

            out = {"pre": 0.0, "post": 0.0}
            for name, _tags, value in registry().snapshot()["points"]:
                if name == "raytpu_collective_dcn_bytes_pre_total":
                    out["pre"] = float(value)
                elif name == "raytpu_collective_dcn_bytes_post_total":
                    out["post"] = float(value)
            return out

        def destroy(self):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(self._group)
            return True

    n_elems = 256 * 1024  # 1 MiB fp32 per rank per op
    coll_mb = n_elems * 4 / 1e6
    world = 4
    for row, slices in (
        ("collective_allreduce_2slice", ["s0", "s0", "s1", "s1"]),
        ("collective_allreduce_1slice", ["s0", "s0", "s0", "s0"]),
    ):
        members = [
            _CollMember.remote(world, r, row, slices[r])
            for r in range(world)
        ]
        strat = ray_tpu.get(
            [m.strategy.remote() for m in members], timeout=120
        )[0]

        def coll_op(ms=members):
            ray_tpu.get(
                [m.allreduce.remote(n_elems) for m in ms], timeout=120
            )

        n, rate = timeit(row, coll_op, 1, min_s=min_s, max_iters=30)
        results[n] = round(rate * coll_mb, 2)
        print(f"  -> {results[n]:.1f} MB/s ({strat})", flush=True)
        if row == "collective_allreduce_2slice":
            b = ray_tpu.get(members[0].dcn_bytes.remote(), timeout=60)
            if b["post"]:
                results["collective_dcn_bytes_ratio"] = round(
                    b["pre"] / b["post"], 3
                )
                print(
                    f"  dcn bytes: {b['pre']:.0f} pre / {b['post']:.0f} "
                    f"post = {results['collective_dcn_bytes_ratio']}x",
                    flush=True,
                )
        # Members destroy first (each tears down the hierarchical subgroup
        # coordinators it owns — killing them outright would leak those
        # actors into the rest of the timed run), then the driver reaps
        # any parent state left behind.
        try:
            ray_tpu.get([m.destroy.remote() for m in members], timeout=60)
        except Exception:
            pass
        from ray_tpu.util import collective as _col

        _col.destroy_collective_group(row)
        for m in members:
            ray_tpu.kill(m)

    # Transport counters: the strace-free syscall-reduction view
    # (PERF.md round-6 A/B rides these).
    from ray_tpu.core import api as _api

    t = _api.transport_stats()
    if t:
        results["transport_frames_sent"] = t["frames_sent"]
        results["transport_writes"] = t["writes"]
        results["transport_frames_per_write"] = round(
            t["frames_per_write"], 3
        )
        results["transport_drains_skipped"] = t["drains_skipped"]
        print(
            f"transport: {t['frames_sent']} frames / {t['writes']} writes "
            f"= {t['frames_per_write']:.2f} frames/write "
            f"(max {t['max_frames_per_write']}, drains awaited "
            f"{t['drains']}, skipped {t['drains_skipped']})",
            flush=True,
        )

    print(json.dumps(results), flush=True)
    ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
