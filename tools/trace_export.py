"""CLI wrapper: export flight-recorder rings as a Chrome trace or a
per-request critical-path breakdown.

The library lives in ``ray_tpu/util/trace_export.py`` (the dashboard's
``/api/v0/timeline`` imports it from there); this entry point exists so
the export sits next to the other operational tools::

    python tools/trace_export.py --out trace.json          # live rings
    python tools/trace_export.py --cluster --out trace.json
    python tools/trace_export.py --dump /tmp/ray_tpu_flightrec/*.json
    python tools/trace_export.py --list-rids
    python tools/trace_export.py --rid fr-1234-0           # breakdown
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from ray_tpu.util.trace_export import (  # noqa: E402,F401  (re-exported API)
    chrome_trace,
    collect_snapshots,
    critical_path,
    load_dumps,
    main,
    request_ids,
)

if __name__ == "__main__":
    sys.exit(main())
